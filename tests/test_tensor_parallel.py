"""Tensor (model) parallelism tests: Megatron-sharded attention/MLP must
match the single-device math exactly on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.sequence import MultiHeadSelfAttention
from deeplearning4j_tpu.parallel.tensor import (
    make_tp_mesh, shard_mha_params, tp_mha, tp_mlp,
)

RNG = np.random.default_rng(0)


def _model_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("model",))


class TestTpMha:
    @pytest.mark.parametrize("n_dev", [2, 4, 8])
    def test_matches_single_device(self, n_dev):
        mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("model",))
        E, H, B, T = 32, 8, 2, 12
        mha = MultiHeadSelfAttention(E, H, impl="blockwise", causal=True)
        params = mha.init(jax.random.PRNGKey(1))
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        ref = mha.apply(params, x)
        sharded = shard_mha_params(params, mesh)
        out = tp_mha(sharded, x, mesh, n_heads=H, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_biases_supported(self):
        mesh = _model_mesh(4)
        E, H, B, T = 16, 4, 1, 6
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        params = {"Wq": jax.random.normal(ks[0], (E, E)) * 0.2,
                  "Wk": jax.random.normal(ks[1], (E, E)) * 0.2,
                  "Wv": jax.random.normal(ks[2], (E, E)) * 0.2,
                  "Wo": jax.random.normal(ks[3], (E, E)) * 0.2,
                  "bq": jnp.arange(E, dtype=jnp.float32) * 0.01,
                  "bk": jnp.ones((E,)) * 0.02,
                  "bv": jnp.ones((E,)) * -0.01,
                  "bo": jnp.ones((E,)) * 0.05}
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        out = tp_mha(shard_mha_params(params, mesh), x, mesh, n_heads=H,
                     causal=False)
        # reference: plain dense math
        d = E // H

        def heads(u):
            return u.reshape(B, T, H, d).transpose(0, 2, 1, 3)

        from deeplearning4j_tpu.parallel.sequence import reference_attention
        q = heads(x @ params["Wq"] + params["bq"])
        k = heads(x @ params["Wk"] + params["bk"])
        v = heads(x @ params["Wv"] + params["bv"])
        o = reference_attention(q, k, v, causal=False)
        ref = (o.transpose(0, 2, 1, 3).reshape(B, T, E) @ params["Wo"]
               + params["bo"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_head_divisibility(self):
        mesh = _model_mesh(8)
        mha = MultiHeadSelfAttention(32, 4, impl="blockwise")
        params = mha.init(jax.random.PRNGKey(0))
        x = jnp.zeros((1, 4, 32))
        with pytest.raises(ValueError):
            tp_mha(shard_mha_params(params, mesh), x, mesh, n_heads=4)


class TestTpMlp:
    def test_matches_dense(self):
        mesh = _model_mesh(8)
        E, F, B, T = 16, 64, 2, 5
        ks = jax.random.split(jax.random.PRNGKey(2), 2)
        params = {"W1": jax.random.normal(ks[0], (E, F)) * 0.1,
                  "b1": jnp.arange(F, dtype=jnp.float32) * 0.01,
                  "W2": jax.random.normal(ks[1], (F, E)) * 0.1,
                  "b2": jnp.ones((E,)) * 0.1}
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        out = tp_mlp(params, x, mesh)
        ref = jax.nn.gelu(x @ params["W1"] + params["b1"]) @ params["W2"] \
            + params["b2"]
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestDpTpMesh:
    def test_composed_axes(self):
        """dp x tp 2-D mesh: tp over 'model' while batch stays whole
        (the composed layout dryrun_multichip exercises)."""
        mesh = make_tp_mesh(2, 4)
        assert mesh.shape == {"data": 2, "model": 4}
        E, H, B, T = 16, 4, 4, 6
        mha = MultiHeadSelfAttention(E, H, impl="blockwise", causal=True)
        params = mha.init(jax.random.PRNGKey(3))
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        ref = mha.apply(params, x)
        out = tp_mha(shard_mha_params(params, mesh), x, mesh, n_heads=H)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestTpGradients:
    def test_gradients_match_unsharded(self):
        """value_and_grad through tp attention+MLP == the unsharded loss
        (check_vma=False disables replication checking, so transpose
        correctness needs an explicit gradient oracle)."""
        mesh = _model_mesh(4)
        E, H, B, T = 16, 4, 2, 8
        mha = MultiHeadSelfAttention(E, H, impl="blockwise", causal=True)
        ap = mha.init(jax.random.PRNGKey(7))
        ks = jax.random.split(jax.random.PRNGKey(8), 2)
        mp = {"W1": jax.random.normal(ks[0], (E, 4 * E)) * 0.1,
              "b1": jnp.zeros((4 * E,)),
              "W2": jax.random.normal(ks[1], (4 * E, E)) * 0.1,
              "b2": jnp.zeros((E,))}
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        y = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)

        def loss_tp(p):
            h = tp_mha(p["attn"], x, mesh, n_heads=H)
            return jnp.mean((tp_mlp(p["mlp"], h, mesh) - y) ** 2)

        def loss_ref(p):
            h = mha.apply(p["attn"], x)
            o = jax.nn.gelu(h @ p["mlp"]["W1"] + p["mlp"]["b1"]) \
                @ p["mlp"]["W2"] + p["mlp"]["b2"]
            return jnp.mean((o - y) ** 2)

        params = {"attn": ap, "mlp": mp}
        l1, g1 = jax.value_and_grad(loss_tp)(params)
        l2, g2 = jax.value_and_grad(loss_ref)(params)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        for path in ("attn", "mlp"):
            for k in g1[path]:
                np.testing.assert_allclose(
                    np.asarray(g1[path][k]), np.asarray(g2[path][k]),
                    atol=2e-5, err_msg=f"{path}/{k}")


class TestPartialBiases:
    def test_missing_output_bias(self):
        """bq/bk/bv without bo (and vice versa) must still be applied."""
        mesh = _model_mesh(4)
        E, H, B, T = 16, 4, 1, 6
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        w = {n: jax.random.normal(k, (E, E)) * 0.2
             for n, k in zip(("Wq", "Wk", "Wv", "Wo"), ks)}
        partial_b = dict(w, bq=jnp.ones((E,)) * 0.3)
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        out_partial = tp_mha(shard_mha_params(partial_b, mesh), x, mesh,
                             n_heads=H, causal=False)
        out_plain = tp_mha(shard_mha_params(w, mesh), x, mesh,
                           n_heads=H, causal=False)
        # the bias must have an effect (not silently dropped)
        assert not np.allclose(np.asarray(out_partial),
                               np.asarray(out_plain))


class TestDpTpComposition:
    def test_batch_axis_shards_data(self):
        """batch_axis='data' on the 2-D mesh: output equals replicated
        run (each data row computes only its shard)."""
        mesh = make_tp_mesh(2, 4)
        E, H, B, T = 16, 4, 4, 6
        mha = MultiHeadSelfAttention(E, H, impl="blockwise", causal=True)
        params = mha.init(jax.random.PRNGKey(3))
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)
        ref = mha.apply(params, x)
        out = tp_mha(shard_mha_params(params, mesh), x, mesh, n_heads=H,
                     batch_axis="data")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)


class TestTpGqa:
    """Tensor-parallel grouped-query attention (VERDICT r2 gap: GQA params
    were rejected by shard_mha_params). KV heads column-shard when
    n_kv_heads % tp == 0; with tp > n_kv_heads the KV params replicate
    and each device slices its group's head (head-group replication).
    Forward AND gradients must equal the unsharded grouped math for every
    (tp, n_kv_heads) combination."""

    def _params(self, E, H, n_kv, seed=3):
        d = E // H
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        return {"wq": jax.random.normal(ks[0], (E, E)) * 0.2,
                "wk": jax.random.normal(ks[1], (E, n_kv * d)) * 0.2,
                "wv": jax.random.normal(ks[2], (E, n_kv * d)) * 0.2,
                "wo": jax.random.normal(ks[3], (E, E)) * 0.2}

    @staticmethod
    def _reference(params, x, H, n_kv):
        from deeplearning4j_tpu.parallel.sequence import reference_attention
        B, T, E = x.shape
        d = E // H

        def heads(u):
            return u.reshape(B, T, -1, d).transpose(0, 2, 1, 3)

        q = heads(x @ params["wq"])
        k = heads(x @ params["wk"])
        v = heads(x @ params["wv"])
        k = jnp.repeat(k, H // n_kv, axis=1)
        v = jnp.repeat(v, H // n_kv, axis=1)
        o = reference_attention(q, k, v, causal=True)
        return o.transpose(0, 2, 1, 3).reshape(B, T, E) @ params["wo"]

    @pytest.mark.parametrize("tp", [1, 2, 4])
    @pytest.mark.parametrize("n_kv", [1, 2, 4])
    def test_forward_and_grads_match_unsharded(self, tp, n_kv):
        E, H, B, T = 16, 4, 2, 8
        mesh = Mesh(np.asarray(jax.devices()[:tp]), ("model",))
        params = self._params(E, H, n_kv)
        x = jnp.asarray(RNG.standard_normal((B, T, E)), jnp.float32)

        ref = self._reference(params, x, H, n_kv)
        sharded = shard_mha_params(params, mesh, n_kv_heads=n_kv,
                                   n_heads=H)
        out = tp_mha(sharded, x, mesh, n_heads=H, n_kv_heads=n_kv,
                     causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"fwd tp={tp} n_kv={n_kv}")

        def loss_tp(p):
            return jnp.sum(tp_mha(p, x, mesh, n_heads=H, n_kv_heads=n_kv,
                                  causal=True) ** 2)

        def loss_ref(p):
            return jnp.sum(self._reference(p, x, H, n_kv) ** 2)

        g_tp = jax.grad(loss_tp)(sharded)
        g_ref = jax.grad(loss_ref)(params)
        for name in params:
            np.testing.assert_allclose(
                np.asarray(g_tp[name]), np.asarray(g_ref[name]),
                atol=2e-4, rtol=2e-4,
                err_msg=f"d{name} tp={tp} n_kv={n_kv}")

    def test_kv_biases_gqa(self):
        E, H, n_kv, tp = 16, 4, 2, 4  # tp > n_kv: replication path
        d = E // H
        mesh = Mesh(np.asarray(jax.devices()[:tp]), ("model",))
        params = self._params(E, H, n_kv)
        params["bq"] = jnp.arange(E, dtype=jnp.float32) * 0.01
        params["bk"] = jnp.arange(n_kv * d, dtype=jnp.float32) * 0.02
        params["bv"] = jnp.ones((n_kv * d,)) * -0.01
        params["bo"] = jnp.ones((E,)) * 0.05
        x = jnp.asarray(RNG.standard_normal((1, 6, E)), jnp.float32)

        from deeplearning4j_tpu.parallel.sequence import reference_attention
        B, T = 1, 6

        def heads(u):
            return u.reshape(B, T, -1, d).transpose(0, 2, 1, 3)

        q = heads(x @ params["wq"] + params["bq"])
        k = heads(x @ params["wk"] + params["bk"])
        v = heads(x @ params["wv"] + params["bv"])
        k = jnp.repeat(k, H // n_kv, axis=1)
        v = jnp.repeat(v, H // n_kv, axis=1)
        o = reference_attention(q, k, v, causal=True)
        ref = (o.transpose(0, 2, 1, 3).reshape(B, T, E) @ params["wo"]
               + params["bo"])

        out = tp_mha(shard_mha_params(params, mesh, n_kv_heads=n_kv,
                                      n_heads=H),
                     x, mesh, n_heads=H, n_kv_heads=n_kv, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_misaligned_rejected(self):
        # tp=4, n_kv=3: neither divides the other -> clear error
        mesh = Mesh(np.asarray(jax.devices()[:4]), ("model",))
        params = self._params(16, 4, n_kv=3)
        # n_heads 4 % n_kv 3 != 0 is itself invalid
        with pytest.raises(ValueError, match="divisible"):
            shard_mha_params(params, mesh, n_kv_heads=3, n_heads=4)

    def test_gqa_needs_n_kv_heads(self):
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))
        params = self._params(16, 4, n_kv=2)
        with pytest.raises(ValueError, match="n_kv_heads"):
            shard_mha_params(params, mesh)
