"""Normalizer + native image pipeline tests (ref: ND4J normalizer tests +
ModelSerializer.addNormalizerToModel round-trip)."""

import os
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize,
)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.datasets.normalizers import normalizer_from_dict
from deeplearning4j_tpu.native import image as nimg

RNG = np.random.default_rng(5)


class TestNormalizerStandardize:
    def test_fit_transform_revert(self):
        x = RNG.normal(5.0, 3.0, (200, 4)).astype(np.float32)
        n = NormalizerStandardize().fit(x)
        z = n.transform(x)
        np.testing.assert_allclose(z.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(z.std(0), 1.0, atol=1e-4)
        np.testing.assert_allclose(n.revert_features(z), x, atol=1e-4)

    def test_per_channel_on_images(self):
        x = RNG.normal(0, 1, (16, 3, 8, 8)).astype(np.float32)
        x[:, 1] += 10.0
        n = NormalizerStandardize().fit(x)
        z = n.transform(x)
        assert abs(z[:, 1].mean()) < 1e-3  # channel axis stats

    def test_iterator_fit_and_dataset_transform(self):
        x = RNG.normal(2, 4, (64, 5)).astype(np.float32)
        y = RNG.normal(0, 1, (64, 2)).astype(np.float32)
        it = ArrayDataSetIterator(x, y, batch_size=16)
        n = NormalizerStandardize()
        n.fit_label(True)
        n.fit(it)
        ds = DataSet(x[:8].copy(), y[:8].copy())
        n.transform(ds)
        assert abs(np.asarray(ds.features).mean()) < 0.5
        np.testing.assert_allclose(n.revert_labels(ds.labels), y[:8],
                                   atol=1e-4)

    def test_json_roundtrip(self):
        import json
        x = RNG.normal(1, 2, (50, 3)).astype(np.float32)
        n = NormalizerStandardize().fit(x)
        n2 = normalizer_from_dict(json.loads(n.to_json()))
        np.testing.assert_allclose(n2.transform(x), n.transform(x))

    def test_unfitted_raises(self):
        with pytest.raises(RuntimeError):
            NormalizerStandardize().transform(np.zeros((2, 2), np.float32))


class TestMinMaxScaler:
    def test_range(self):
        x = RNG.uniform(-7, 3, (100, 4)).astype(np.float32)
        n = NormalizerMinMaxScaler(lo=-1, hi=1).fit(x)
        z = n.transform(x)
        np.testing.assert_allclose(z.min(0), -1.0, atol=1e-5)
        np.testing.assert_allclose(z.max(0), 1.0, atol=1e-5)
        np.testing.assert_allclose(n.revert_features(z), x, atol=1e-4)


class TestImageScaler:
    def test_u8_batch_native_path(self):
        imgs = RNG.integers(0, 256, (6, 10, 12, 3), np.uint8)
        n = ImagePreProcessingScaler()
        out = n.transform(imgs)
        assert out.shape == (6, 3, 10, 12)  # NHWC u8 -> NCHW f32
        assert out.dtype == np.float32
        np.testing.assert_allclose(
            out, imgs.transpose(0, 3, 1, 2).astype(np.float32) / 255.0,
            atol=1e-6)

    def test_float_passthrough_range(self):
        x = np.full((2, 3, 4, 4), 255.0, np.float32)
        n = ImagePreProcessingScaler(lo=-1, hi=1)
        np.testing.assert_allclose(n.transform(x), 1.0, atol=1e-6)
        np.testing.assert_allclose(n.revert_features(n.transform(x)), x,
                                   atol=1e-3)


class TestCheckpointEmbed:
    def test_add_and_restore(self):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.util.model_serializer import (
            add_normalizer_to_model, restore_normalizer_from_file,
            restore_multi_layer_network, write_model,
        )
        conf = (NeuralNetConfiguration.Builder().seed(1).list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.normal(3, 2, (40, 3)).astype(np.float32)
        norm = NormalizerStandardize().fit(x)
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "m.zip")
            write_model(net, p)
            assert restore_normalizer_from_file(p) is None
            add_normalizer_to_model(p, norm)
            with pytest.raises(ValueError):
                add_normalizer_to_model(p, norm)  # double-embed rejected
            restored = restore_normalizer_from_file(p)
            np.testing.assert_allclose(restored.transform(x),
                                       norm.transform(x))
            # the model itself still restores
            net2 = restore_multi_layer_network(p)
            assert net2 is not None


class TestNativeImageOps:
    def test_resize_native_matches_fallback(self):
        imgs = RNG.integers(0, 256, (3, 17, 23, 3), np.uint8)
        a = nimg.resize_bilinear(imgs, 8, 12)
        assert a.shape == (3, 8, 12, 3)
        if nimg.native_available():
            # force fallback and compare
            nat = nimg._NATIVE
            lib, nat._lib = nat._lib, None
            so, nat.so_path = nat.so_path, "/nonexistent.so"
            try:
                b = nimg.resize_bilinear(imgs, 8, 12)
            finally:
                nat._lib, nat.so_path = lib, so
            assert np.max(np.abs(a.astype(int) - b.astype(int))) <= 1

    def test_crop_flip(self):
        imgs = np.arange(2 * 6 * 6 * 1, dtype=np.uint8).reshape(2, 6, 6, 1)
        out = nimg.crop_flip(imgs, 4, 4, np.array([1, 0]), np.array([2, 1]),
                             flips=np.array([0, 1], np.uint8))
        np.testing.assert_array_equal(out[0], imgs[0, 1:5, 2:6])
        np.testing.assert_array_equal(out[1], imgs[1, 0:4, 1:5][:, ::-1])
        with pytest.raises(ValueError):
            nimg.crop_flip(imgs, 4, 4, np.array([3, 0]), np.array([0, 0]))

    def test_fused_normalize_pack(self):
        imgs = RNG.integers(0, 256, (4, 5, 6, 3), np.uint8)
        mean = np.array([0.4, 0.5, 0.6], np.float32)
        std = np.array([0.2, 0.3, 0.1], np.float32)
        out = nimg.u8hwc_to_f32chw(imgs, mean=mean, std=std)
        ref = (imgs.astype(np.float32) / 255.0 - mean) / std
        np.testing.assert_allclose(out, ref.transpose(0, 3, 1, 2), atol=1e-5)
