"""Dataset fetcher tests (ref: deeplearning4j-core datasets tests,
MnistFetcherTest pattern — local IDX fixtures instead of downloads)."""

import gzip
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    CifarDataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
    MnistDataSetIterator,
)


def write_idx(path, arr):
    codes = {np.uint8: 0x08, np.int32: 0x0C}
    with open(path, "wb") as f:
        f.write(bytes([0, 0, codes[arr.dtype.type], arr.ndim]))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(arr.dtype.newbyteorder(">")).tobytes())


@pytest.fixture
def mnist_dir(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (40, 28, 28), np.uint8)
    labels = rng.integers(0, 10, 40).astype(np.uint8)
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), labels)
    # test split stored gzipped to exercise the .gz path
    t_imgs = rng.integers(0, 256, (10, 28, 28), np.uint8)
    t_labels = rng.integers(0, 10, 10).astype(np.uint8)
    write_idx(str(tmp_path / "_ti"), t_imgs)
    write_idx(str(tmp_path / "_tl"), t_labels)
    for src, dst in (("_ti", "t10k-images-idx3-ubyte.gz"),
                     ("_tl", "t10k-labels-idx1-ubyte.gz")):
        with open(tmp_path / src, "rb") as fin, \
                gzip.open(tmp_path / dst, "wb") as fout:
            fout.write(fin.read())
    return str(tmp_path), imgs, labels


class TestMnist:
    def test_batches(self, mnist_dir):
        d, imgs, labels = mnist_dir
        it = MnistDataSetIterator(16, train=True, data_dir=d, shuffle=False)
        batches = list(it)
        assert [b.features.shape[0] for b in batches] == [16, 16, 8]
        assert batches[0].features.shape == (16, 784)
        assert batches[0].labels.shape == (16, 10)
        np.testing.assert_allclose(
            batches[0].features[0], imgs[0].reshape(-1) / 255.0, atol=1e-6)
        assert batches[0].labels[0].argmax() == labels[0]
        assert batches[0].features.min() >= 0 and batches[0].features.max() <= 1

    def test_gz_decompression(self, mnist_dir):
        d, _, _ = mnist_dir
        it = MnistDataSetIterator(10, train=False, data_dir=d)
        assert sum(b.features.shape[0] for b in it) == 10

    def test_channels_shape(self, mnist_dir):
        d, _, _ = mnist_dir
        it = MnistDataSetIterator(8, train=True, data_dir=d, flatten=False)
        b = next(iter(it))
        assert b.features.shape == (8, 1, 28, 28)

    def test_missing_files_error(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="zero-egress"):
            MnistDataSetIterator(8, data_dir=str(tmp_path))

    def test_synthetic(self):
        it = MnistDataSetIterator(32, synthetic=True, num_examples=64)
        b = next(iter(it))
        assert b.features.shape == (32, 784)


class TestEmnist:
    def test_letters_split(self, tmp_path):
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (20, 28, 28), np.uint8)
        labels = (rng.integers(0, 26, 20) + 1).astype(np.uint8)  # 1-based
        write_idx(str(tmp_path / "emnist-letters-train-images-idx3-ubyte"),
                  imgs)
        write_idx(str(tmp_path / "emnist-letters-train-labels-idx1-ubyte"),
                  labels)
        it = EmnistDataSetIterator(10, split="letters", train=True,
                                   data_dir=str(tmp_path))
        b = next(iter(it))
        assert b.labels.shape[1] == 26  # 0-based one-hot after shift

    def test_unknown_split(self):
        with pytest.raises(ValueError, match="unknown EMNIST split"):
            EmnistDataSetIterator(8, split="bogus")


class TestCifar:
    def test_binary_format(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 12
        recs = np.zeros((n, 3073), np.uint8)
        recs[:, 0] = rng.integers(0, 10, n)
        recs[:, 1:] = rng.integers(0, 256, (n, 3072))
        for name in CifarDataSetIterator.TRAIN_FILES:
            recs.tofile(str(tmp_path / name))
        it = CifarDataSetIterator(8, train=True, data_dir=str(tmp_path),
                                  seed=3)
        total = 0
        for b in it:
            assert b.features.shape[1:] == (3, 32, 32)
            assert b.features.max() <= 1.0
            total += b.features.shape[0]
        assert total == n * 5

    def test_synthetic(self):
        it = CifarDataSetIterator(16, synthetic=True, num_examples=32)
        b = next(iter(it))
        assert b.features.shape == (16, 3, 32, 32)


class TestIris:
    def test_csv_loading(self, tmp_path):
        rng = np.random.default_rng(4)
        rows = np.column_stack([rng.standard_normal((30, 4)),
                                rng.integers(0, 3, 30)])
        np.savetxt(str(tmp_path / "iris.csv"), rows, delimiter=",",
                   fmt="%.5g")
        it = IrisDataSetIterator(batch_size=30, num_examples=30,
                                 data_dir=str(tmp_path))
        b = next(iter(it))
        assert b.features.shape == (30, 4)
        assert b.labels.shape == (30, 3)
        np.testing.assert_allclose(b.features, rows[:, :4], rtol=1e-4)

    def test_fallback_trains(self):
        # synthetic iris should be learnable by a small softmax net
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        it = IrisDataSetIterator(batch_size=150)  # full batch: file is
        # ordered by class, and per-class minibatches destabilize SGD
        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        net.fit(it, epochs=80)
        b = next(iter(IrisDataSetIterator(batch_size=150)))
        acc = (np.asarray(net.output(b.features)).argmax(1)
               == b.labels.argmax(1)).mean()
        assert acc > 0.85, f"iris accuracy {acc}"


class TestLFW:
    def _make_lfw(self, root):
        """Tiny lfw/ tree: 3 people, 2-4 images each."""
        from PIL import Image
        base = os.path.join(root, "lfw")
        rng = np.random.default_rng(5)
        counts = {"Aaron_A": 4, "Betty_B": 2, "Carl_C": 3}
        for person, n in counts.items():
            d = os.path.join(base, person)
            os.makedirs(d)
            for i in range(n):
                a = rng.integers(0, 256, (40, 30, 3), np.uint8)
                Image.fromarray(a).save(os.path.join(d, f"{person}_{i}.jpg"))
        return base

    def test_directory_layout(self, tmp_path):
        from deeplearning4j_tpu.datasets import LFWDataSetIterator
        self._make_lfw(str(tmp_path))
        it = LFWDataSetIterator(batch_size=4, image_shape=(24, 24, 3),
                                data_dir=str(tmp_path))
        assert it.num_classes == 3
        assert it.label_names == ["Aaron_A", "Betty_B", "Carl_C"]
        ds = next(iter(it))
        assert ds.features.shape == (4, 3, 24, 24)
        assert ds.labels.shape == (4, 3)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    def test_num_labels_subset(self, tmp_path):
        from deeplearning4j_tpu.datasets import LFWDataSetIterator
        self._make_lfw(str(tmp_path))
        it = LFWDataSetIterator(batch_size=4, image_shape=(16, 16, 1),
                                data_dir=str(tmp_path), num_labels=2,
                                train=False, split_train_test=0.5)
        # 2 most frequent identities: Aaron_A (4), Carl_C (3)
        assert it.label_names == ["Aaron_A", "Carl_C"]
        ds = next(iter(it))
        assert ds.features.shape[1:] == (1, 16, 16)

    def test_synthetic(self):
        from deeplearning4j_tpu.datasets import LFWDataSetIterator
        it = LFWDataSetIterator(batch_size=8, image_shape=(32, 32, 3),
                                num_examples=24, num_labels=4,
                                synthetic=True)
        batches = list(it)
        assert sum(b.features.shape[0] for b in batches) == 24
        assert batches[0].labels.shape[1] == 4


class TestSvhn:
    def test_mat_format(self, tmp_path):
        from scipy.io import savemat
        from deeplearning4j_tpu.datasets import SvhnDataSetIterator
        rng = np.random.default_rng(3)
        n = 12
        X = rng.integers(0, 256, (32, 32, 3, n), np.uint8)
        y = rng.integers(1, 11, (n, 1))  # matlab labels 1..10
        savemat(os.path.join(tmp_path, "train_32x32.mat"), {"X": X, "y": y})
        it = SvhnDataSetIterator(batch_size=6, data_dir=str(tmp_path),
                                 train=True)
        ds = next(iter(it))
        assert ds.features.shape == (6, 3, 32, 32)
        assert ds.labels.shape == (6, 10)

    def test_label_ten_remaps_to_zero(self, tmp_path):
        from scipy.io import savemat
        from deeplearning4j_tpu.datasets import SvhnDataSetIterator
        X = np.zeros((32, 32, 3, 2), np.uint8)
        savemat(os.path.join(tmp_path, "test_32x32.mat"),
                {"X": X, "y": np.array([[10], [3]])})
        it = SvhnDataSetIterator(batch_size=2, data_dir=str(tmp_path),
                                 train=False)
        labels = np.asarray(next(iter(it)).labels)
        assert labels[0].argmax() == 0 and labels[0].sum() == 1
        assert labels[1].argmax() == 3

    def test_pixel_transpose_correct(self, tmp_path):
        """X[h,w,c,n] must land at features[n,c,h,w]."""
        from scipy.io import savemat
        from deeplearning4j_tpu.datasets import SvhnDataSetIterator
        X = np.zeros((32, 32, 3, 1), np.uint8)
        X[2, 5, 1, 0] = 255  # h=2, w=5, channel=1
        savemat(os.path.join(tmp_path, "test_32x32.mat"),
                {"X": X, "y": np.array([[3]])})
        it = SvhnDataSetIterator(batch_size=1, data_dir=str(tmp_path),
                                 train=False)
        ds = next(iter(it))
        f = np.asarray(ds.features)
        assert f[0, 1, 2, 5] == 1.0 and f.sum() == 1.0

    def test_synthetic(self):
        from deeplearning4j_tpu.datasets import SvhnDataSetIterator
        it = SvhnDataSetIterator(batch_size=16, synthetic=True,
                                 num_examples=32)
        ds = next(iter(it))
        assert ds.features.shape == (16, 3, 32, 32)


class TestBenchmarkIterator:
    def test_same_batch_repeated(self):
        from deeplearning4j_tpu.datasets import BenchmarkDataSetIterator
        it = BenchmarkDataSetIterator((8, 3, 16, 16), num_labels=5,
                                      total_batches=4)
        batches = list(it)
        assert len(batches) == 4
        assert batches[0].features.shape == (8, 3, 16, 16)
        assert batches[0] is batches[3]  # the SAME object: zero ETL cost
        assert batches[0].labels.sum() == 8


class TestLocalUnstructuredDataFormatter:
    """ref: datasets/rearrange/LocalUnstructuredDataFormatter.java."""

    def _corpus(self, tmp_path):
        src = tmp_path / "raw"
        for cls in ("cats", "dogs"):
            d = src / cls
            d.mkdir(parents=True)
            for i in range(5):
                (d / f"img{i:02d}-{cls[:-1]}.jpg").write_bytes(b"x" * 10)
        return src

    def test_directory_labeling_split(self, tmp_path):
        from deeplearning4j_tpu.datasets.formatter import (
            LocalUnstructuredDataFormatter,
        )
        src = self._corpus(tmp_path)
        f = LocalUnstructuredDataFormatter(str(tmp_path / "out"), str(src),
                                           labeling_type="directory",
                                           percent_train=0.8, seed=1)
        f.rearrange()
        assert f.get_num_examples_total() == 10
        assert f.get_num_examples_to_train_on() == 8
        assert f.get_num_test_examples() == 2
        import os
        train_files = [os.path.join(d, n) for d, _, ns in
                       os.walk(tmp_path / "out" / "split" / "train")
                       for n in ns]
        test_files = [os.path.join(d, n) for d, _, ns in
                      os.walk(tmp_path / "out" / "split" / "test")
                      for n in ns]
        assert len(train_files) == 8 and len(test_files) == 2
        # labels are parent dir names
        labels = {os.path.basename(os.path.dirname(p))
                  for p in train_files + test_files}
        assert labels <= {"cats", "dogs"}

    def test_name_labeling(self, tmp_path):
        from deeplearning4j_tpu.datasets.formatter import (
            LocalUnstructuredDataFormatter,
        )
        src = self._corpus(tmp_path)
        f = LocalUnstructuredDataFormatter(str(tmp_path / "out"), str(src),
                                           labeling_type="name",
                                           percent_train=0.5, seed=2)
        assert f.get_name_label("a/img00-cat.jpg") == "cat"
        f.rearrange()
        import os
        labels = set(os.listdir(tmp_path / "out" / "split" / "train"))
        assert labels <= {"cat", "dog"}

    def test_existing_split_rejected(self, tmp_path):
        import pytest
        from deeplearning4j_tpu.datasets.formatter import (
            LocalUnstructuredDataFormatter,
        )
        (tmp_path / "out" / "split").mkdir(parents=True)
        with pytest.raises(RuntimeError, match="already exists"):
            LocalUnstructuredDataFormatter(str(tmp_path / "out"),
                                           str(tmp_path))

    def test_get_new_destination(self, tmp_path):
        from deeplearning4j_tpu.datasets.formatter import (
            LocalUnstructuredDataFormatter,
        )
        f = LocalUnstructuredDataFormatter(str(tmp_path / "out"),
                                           str(tmp_path / "raw"),
                                           labeling_type="directory")
        dst = f.get_new_destination("/data/cats/a.jpg", train=True)
        assert dst.endswith("split/train/cats/a.jpg")
