"""Dataset fetcher tests (ref: deeplearning4j-core datasets tests,
MnistFetcherTest pattern — local IDX fixtures instead of downloads)."""

import gzip
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    CifarDataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
    MnistDataSetIterator,
)


def write_idx(path, arr):
    codes = {np.uint8: 0x08, np.int32: 0x0C}
    with open(path, "wb") as f:
        f.write(bytes([0, 0, codes[arr.dtype.type], arr.ndim]))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.astype(arr.dtype.newbyteorder(">")).tobytes())


@pytest.fixture
def mnist_dir(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (40, 28, 28), np.uint8)
    labels = rng.integers(0, 10, 40).astype(np.uint8)
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs)
    write_idx(str(tmp_path / "train-labels-idx1-ubyte"), labels)
    # test split stored gzipped to exercise the .gz path
    t_imgs = rng.integers(0, 256, (10, 28, 28), np.uint8)
    t_labels = rng.integers(0, 10, 10).astype(np.uint8)
    write_idx(str(tmp_path / "_ti"), t_imgs)
    write_idx(str(tmp_path / "_tl"), t_labels)
    for src, dst in (("_ti", "t10k-images-idx3-ubyte.gz"),
                     ("_tl", "t10k-labels-idx1-ubyte.gz")):
        with open(tmp_path / src, "rb") as fin, \
                gzip.open(tmp_path / dst, "wb") as fout:
            fout.write(fin.read())
    return str(tmp_path), imgs, labels


class TestMnist:
    def test_batches(self, mnist_dir):
        d, imgs, labels = mnist_dir
        it = MnistDataSetIterator(16, train=True, data_dir=d, shuffle=False)
        batches = list(it)
        assert [b.features.shape[0] for b in batches] == [16, 16, 8]
        assert batches[0].features.shape == (16, 784)
        assert batches[0].labels.shape == (16, 10)
        np.testing.assert_allclose(
            batches[0].features[0], imgs[0].reshape(-1) / 255.0, atol=1e-6)
        assert batches[0].labels[0].argmax() == labels[0]
        assert batches[0].features.min() >= 0 and batches[0].features.max() <= 1

    def test_gz_decompression(self, mnist_dir):
        d, _, _ = mnist_dir
        it = MnistDataSetIterator(10, train=False, data_dir=d)
        assert sum(b.features.shape[0] for b in it) == 10

    def test_channels_shape(self, mnist_dir):
        d, _, _ = mnist_dir
        it = MnistDataSetIterator(8, train=True, data_dir=d, flatten=False)
        b = next(iter(it))
        assert b.features.shape == (8, 1, 28, 28)

    def test_missing_files_error(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="zero-egress"):
            MnistDataSetIterator(8, data_dir=str(tmp_path))

    def test_synthetic(self):
        it = MnistDataSetIterator(32, synthetic=True, num_examples=64)
        b = next(iter(it))
        assert b.features.shape == (32, 784)


class TestEmnist:
    def test_letters_split(self, tmp_path):
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (20, 28, 28), np.uint8)
        labels = (rng.integers(0, 26, 20) + 1).astype(np.uint8)  # 1-based
        write_idx(str(tmp_path / "emnist-letters-train-images-idx3-ubyte"),
                  imgs)
        write_idx(str(tmp_path / "emnist-letters-train-labels-idx1-ubyte"),
                  labels)
        it = EmnistDataSetIterator(10, split="letters", train=True,
                                   data_dir=str(tmp_path))
        b = next(iter(it))
        assert b.labels.shape[1] == 26  # 0-based one-hot after shift

    def test_unknown_split(self):
        with pytest.raises(ValueError, match="unknown EMNIST split"):
            EmnistDataSetIterator(8, split="bogus")


class TestCifar:
    def test_binary_format(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 12
        recs = np.zeros((n, 3073), np.uint8)
        recs[:, 0] = rng.integers(0, 10, n)
        recs[:, 1:] = rng.integers(0, 256, (n, 3072))
        for name in CifarDataSetIterator.TRAIN_FILES:
            recs.tofile(str(tmp_path / name))
        it = CifarDataSetIterator(8, train=True, data_dir=str(tmp_path),
                                  seed=3)
        total = 0
        for b in it:
            assert b.features.shape[1:] == (3, 32, 32)
            assert b.features.max() <= 1.0
            total += b.features.shape[0]
        assert total == n * 5

    def test_synthetic(self):
        it = CifarDataSetIterator(16, synthetic=True, num_examples=32)
        b = next(iter(it))
        assert b.features.shape == (16, 3, 32, 32)


class TestIris:
    def test_csv_loading(self, tmp_path):
        rng = np.random.default_rng(4)
        rows = np.column_stack([rng.standard_normal((30, 4)),
                                rng.integers(0, 3, 30)])
        np.savetxt(str(tmp_path / "iris.csv"), rows, delimiter=",",
                   fmt="%.5g")
        it = IrisDataSetIterator(batch_size=30, num_examples=30,
                                 data_dir=str(tmp_path))
        b = next(iter(it))
        assert b.features.shape == (30, 4)
        assert b.labels.shape == (30, 3)
        np.testing.assert_allclose(b.features, rows[:, :4], rtol=1e-4)

    def test_fallback_trains(self):
        # synthetic iris should be learnable by a small softmax net
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        it = IrisDataSetIterator(batch_size=150)  # full batch: file is
        # ordered by class, and per-class minibatches destabilize SGD
        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf)
        net.init()
        net.fit(it, epochs=80)
        b = next(iter(IrisDataSetIterator(batch_size=150)))
        acc = (np.asarray(net.output(b.features)).argmax(1)
               == b.labels.argmax(1)).mean()
        assert acc > 0.85, f"iris accuracy {acc}"
