"""Annotation pipeline + tree corpus tests (the dl4j-nlp-uima role).

Mirrors the reference module's observable behavior: sentence segmentation,
token spans, stemming, POS filtering with "NONE" substitution
(PosUimaTokenizer.java), SentiWordNet scoring with negation flip and the
harmonic sense weighting (SWN3.java), and the tree pipeline
(TreeVectorizer.java: binarize + collapse unaries + gold labels).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.annotation import (
    AnalysisEngine, Annotation, AnnotatedDocument, AnnotationSentenceIterator,
    AnnotationTokenizerFactory, PosAnnotator, PosFilterTokenizerFactory,
    SWN3, SentenceAnnotator, StemmingPreprocessor, TokenizerAnnotator,
    porter_stem,
)
from deeplearning4j_tpu.nlp.trees import (
    BinarizeTreeTransformer, ChunkTreeParser, CollapseUnaries,
    HeadWordFinder, Tree, TreeIterator, TreeVectorizer,
)


class TestSentenceAnnotator:
    def test_splits_on_boundaries(self):
        doc = AnalysisEngine.segmenter().process(
            "The cat sat. The dog barked! Did it rain? Yes.")
        sents = doc.select("sentence")
        texts = [doc.covered_text(s) for s in sents]
        assert texts == ["The cat sat.", "The dog barked!", "Did it rain?",
                         "Yes."]

    def test_abbreviations_kept_whole(self):
        doc = AnalysisEngine.segmenter().process(
            "Dr. Smith arrived. He sat down.")
        texts = [doc.covered_text(s) for s in doc.select("sentence")]
        assert texts == ["Dr. Smith arrived.", "He sat down."]

    def test_spans_index_into_text(self):
        text = "One two.  Three four."
        doc = AnalysisEngine.segmenter().process(text)
        for s in doc.select("sentence"):
            assert text[s.begin:s.end] == doc.covered_text(s)


class TestTokenizerAnnotator:
    def test_token_spans(self):
        text = "It's 3.5 degrees, okay?"
        doc = AnalysisEngine.tokenizer(stem=False).process(text)
        words = [doc.covered_text(t) for t in doc.select("token")]
        assert words == ["It's", "3.5", "degrees", ",", "okay", "?"]

    def test_covered_tokens_per_sentence(self):
        doc = AnalysisEngine.tokenizer(stem=False).process(
            "First here. Second there.")
        sents = doc.select("sentence")
        assert len(sents) == 2
        first = [doc.covered_text(t) for t in doc.covered(sents[0], "token")]
        assert first == ["First", "here", "."]


class TestPorterStemmer:
    # classic Porter (1980) reference pairs
    @pytest.mark.parametrize("word,stem", [
        ("caresses", "caress"), ("ponies", "poni"), ("cats", "cat"),
        ("feed", "feed"), ("agreed", "agre"), ("plastered", "plaster"),
        ("motoring", "motor"), ("sing", "sing"), ("conflated", "conflat"),
        ("troubling", "troubl"), ("sized", "size"), ("hopping", "hop"),
        ("falling", "fall"), ("hissing", "hiss"), ("happy", "happi"),
        ("relational", "relat"), ("conditional", "condit"),
        ("vietnamization", "vietnam"), ("predication", "predic"),
        ("operator", "oper"), ("feudalism", "feudal"),
        ("decisiveness", "decis"), ("hopefulness", "hope"),
        ("formality", "formal"), ("sensitivity", "sensit"),
        ("triplicate", "triplic"), ("formative", "form"),
        ("formalize", "formal"), ("electrical", "electr"),
        ("hopeful", "hope"), ("goodness", "good"),
        ("revival", "reviv"), ("allowance", "allow"),
        ("inference", "infer"), ("airliner", "airlin"),
        ("adjustable", "adjust"), ("defensible", "defens"),
        ("replacement", "replac"), ("adjustment", "adjust"),
        ("dependent", "depend"), ("adoption", "adopt"),
        ("activate", "activ"), ("effective", "effect"),
        ("probate", "probat"), ("rate", "rate"), ("cease", "ceas"),
        ("controll", "control"), ("roll", "roll"),
    ])
    def test_reference_pairs(self, word, stem):
        assert porter_stem(word) == stem

    def test_preprocessor(self):
        pre = StemmingPreprocessor()
        assert pre("Running".lower()) == "run"


class TestPosAnnotator:
    def test_basic_tags(self):
        doc = AnalysisEngine.pos_tagger().process("The cat quickly ate food.")
        tags = {doc.covered_text(t): t.features["pos"]
                for t in doc.select("token")}
        assert tags["The"] == "DT"
        assert tags["quickly"] == "RB"
        assert tags["cat"] == "NN"
        assert tags["."] == "."

    def test_verb_after_modal(self):
        doc = AnalysisEngine.pos_tagger().process("it can jump")
        tags = [t.features["pos"] for t in doc.select("token")]
        assert tags == ["PRP", "MD", "VB"]


class TestIteratorsAndFactories:
    def test_sentence_iterator(self):
        it = AnnotationSentenceIterator(
            ["A first one. A second one.", "A third one."])
        assert list(it) == ["A first one.", "A second one.", "A third one."]

    def test_tokenizer_factory_stems(self):
        fac = AnnotationTokenizerFactory()
        toks = fac.create("The cats were running").get_tokens()
        assert "cat" in toks and "run" in toks

    def test_tokenizer_factory_raw(self):
        fac = AnnotationTokenizerFactory(use_stems=False)
        assert fac.create("The cats ran").get_tokens() == \
            ["The", "cats", "ran"]

    def test_pos_filter_none_substitution(self):
        # ref PosUimaTokenizer: "any not valid part of speech tags become
        # NONE"
        fac = PosFilterTokenizerFactory(["NN", "NNS"],
                                        engine=AnalysisEngine([
                                            SentenceAnnotator(),
                                            TokenizerAnnotator(),
                                            PosAnnotator()]))
        toks = fac.create("the cat sat").get_tokens()
        assert toks == ["NONE", "cat", "NONE"]

    def test_pos_filter_strip_nones(self):
        fac = PosFilterTokenizerFactory(["NN", "NNS"], strip_nones=True,
                                        engine=AnalysisEngine([
                                            SentenceAnnotator(),
                                            TokenizerAnnotator(),
                                            PosAnnotator()]))
        assert fac.create("the cat sat").get_tokens() == ["cat"]


SWN_FIXTURE = """# POS\tID\tPosScore\tNegScore\tSynsetTerms\tGloss
a\t00001\t0.75\t0\tgood#1\tfine quality
a\t00002\t0.5\t0.125\tgood#2 great#1\tsecond sense
a\t00003\t0\t0.875\tbad#1\tpoor quality
n\t00004\t0\t0.25\tbad#2\tnoun sense
"""


class TestSWN3(object):
    @pytest.fixture
    def swn(self, tmp_path):
        p = tmp_path / "swn.tsv"
        p.write_text(SWN_FIXTURE)
        return SWN3(str(p))

    def test_harmonic_sense_weighting(self, swn):
        # good#a: senses 1:0.75, 2:0.375 → (0.75/1 + 0.375/2)/(1 + 1/2)
        expected = (0.75 + 0.375 / 2) / 1.5
        assert swn._dict["good#a"] == pytest.approx(expected)

    def test_extract_sums_pos_entries(self, swn):
        # bad appears as adjective and noun; extract() sums both
        assert swn.extract("bad") == pytest.approx(
            swn._dict["bad#a"] + swn._dict["bad#n"])

    def test_score_and_classify(self, swn):
        assert swn.score("A good day") > 0
        assert swn.classify("A good day").endswith("positive")
        assert swn.score("A bad day") < 0

    def test_negation_flips(self, swn):
        plain = swn.score("It is good")
        negated = swn.score("It is not good")
        assert negated == pytest.approx(-plain)

    def test_contracted_negation_flips(self, swn):
        # the tokenizer keeps "isn't" whole; the n't-suffix check must fire
        plain = swn.score("It is good")
        negated = swn.score("It isn't good")
        assert negated == pytest.approx(-plain)

    def test_class_boundaries(self, swn):
        assert swn.class_for_score(0.8) == "strong_positive"
        assert swn.class_for_score(0.4) == "positive"
        assert swn.class_for_score(0.1) == "weak_positive"
        assert swn.class_for_score(0.0) == "neutral"
        assert swn.class_for_score(-0.1) == "weak_negative"
        assert swn.class_for_score(-0.4) == "negative"
        assert swn.class_for_score(-0.9) == "strong_negative"


class TestTrees:
    def test_parse_produces_chunked_tree(self):
        trees = ChunkTreeParser().get_trees("The cat sat on the mat.")
        assert len(trees) == 1
        t = trees[0]
        assert t.label == "S"
        assert t.yield_words() == ["The", "cat", "sat", "on", "the", "mat",
                                   "."]
        labels = [c.label for c in t.children]
        assert "NP" in labels and "VP" in labels and "PP" in labels

    def test_spans_cover_text(self):
        text = "Dogs chase cats."
        t = ChunkTreeParser().get_trees(text)[0]
        for leaf in t.leaves():
            assert text[leaf.begin:leaf.end] == leaf.value

    def test_binarize_max_two_children(self):
        wide = Tree("S", [Tree("A", [Tree(value=str(i))]) for i in range(5)])
        out = BinarizeTreeTransformer().transform(wide)
        stack = [out]
        while stack:
            n = stack.pop()
            assert len(n.children) <= 2
            stack.extend(n.children)
        # surface order preserved
        assert out.yield_words() == [str(i) for i in range(5)]

    def test_collapse_unaries(self):
        chain = Tree("S", [Tree("NP", [Tree("NX", [
            Tree("NN", [Tree(value="cat")]),
            Tree("NN", [Tree(value="dog")])])])])
        out = CollapseUnaries().transform(chain)
        # S→NP→NX chain collapsed: top label kept, bottom node's children
        # promoted
        assert out.label == "S"
        assert len(out.children) == 2
        assert all(c.is_preterminal() for c in out.children)
        assert out.yield_words() == ["cat", "dog"]

    def test_preterminals_survive_collapse(self):
        pre = Tree("NN", [Tree(value="cat")])
        assert CollapseUnaries().transform(pre).is_preterminal()

    def test_head_word_finder(self):
        # (S (NP (DT the) (NN cat)) (VP (VBD sat)))
        t = Tree("S", [
            Tree("NP", [Tree("DT", [Tree(value="the")]),
                        Tree("NN", [Tree(value="cat")])]),
            Tree("VP", [Tree("VBD", [Tree(value="sat")])])])
        finder = HeadWordFinder()
        assert finder.find_head(t).value == "sat"      # S → VP → VBD
        assert finder.find_head(t.children[0]).value == "cat"  # NP → NN

    def test_vectorizer_labels_and_vectors(self):
        lookup = {"cats": np.ones(4, np.float32),
                  "sleep": np.full(4, 2.0, np.float32)}
        vec = TreeVectorizer(lookup=lookup)
        trees = vec.get_trees_with_labels("Cats sleep.", "pos",
                                          ["neg", "pos"])
        t = trees[0]
        stack = [t]
        while stack:
            n = stack.pop()
            assert n.gold_label == 1
            assert len(n.children) <= 2
            stack.extend(n.children)
        leaf_vecs = {leaf.value: leaf.vector for leaf in t.leaves()}
        np.testing.assert_array_equal(leaf_vecs["Cats"], np.ones(4))
        np.testing.assert_array_equal(leaf_vecs["sleep"], np.full(4, 2.0))
        # OOV leaf (the period) gets a zero vector of the right dim
        np.testing.assert_array_equal(leaf_vecs["."], np.zeros(4))

    def test_tree_iterator_batches(self):
        docs = [("One cat sat. Two dogs ran.", "pos"),
                ("It was bad.", "neg")]
        batches = list(TreeIterator(docs, ["neg", "pos"], batch_size=2))
        trees = [t for b in batches for t in b]
        assert len(trees) == 3
        assert trees[0].gold_label == 1 and trees[2].gold_label == 0
        assert all(len(b) <= 2 for b in batches)

    def test_error_sum_and_clone(self):
        t = Tree("S", [Tree("NN", [Tree(value="x")])])
        t.error, t.children[0].error = 1.5, 2.0
        assert t.error_sum() == pytest.approx(3.5)
        c = t.clone()
        assert repr(c) == repr(t)
        c.children[0].error = 0.0
        assert t.children[0].error == 2.0
