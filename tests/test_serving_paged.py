"""Serving engine v2 (paged KV arena + prefix cache + in-engine
speculation): bit-exactness vs one-shot sample_stream and vs the slot
arena, token-budget admission (incl. the oversized-request submit
rejection), page lifecycle/eviction, chaos page exhaustion, telemetry,
and the zero-retraces-after-warmup guard with every mode on."""

import numpy as np
import pytest

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import runtime
from deeplearning4j_tpu.monitoring.metrics import MetricsRegistry
from deeplearning4j_tpu.resilience import chaos
from deeplearning4j_tpu.serving import (
    GenerationEngine, PagedKVConfig, SpeculationConfig)
from deeplearning4j_tpu.serving.health import (
    SERVING_KV_PAGES_TOTAL, SERVING_KV_PAGES_USED, SERVING_PREFIX_HITS,
    SERVING_PREFIX_MISSES, SERVING_SPEC_ACCEPTANCE)
from deeplearning4j_tpu.util.decoding import prompt_lookup_proposer
from deeplearning4j_tpu.zoo import (
    TextGenerationLSTM, TextGenerationTransformer)

V = 12
PROMPTS = [[1, 2, 3, 4, 5], [6, 7], [8, 9, 10, 1], [2, 4, 6], [3],
           [5, 5, 9]]
SYS = [7, 3, 9, 1, 4, 2, 8, 5]          # a ps=4 / ps=8 aligned prefix


@pytest.fixture(scope="module")
def rope_model():
    return TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                     n_heads=2, n_layers=2,
                                     max_length=32, positional="rope")


@pytest.fixture(scope="module")
def rope_net(rope_model):
    return rope_model.init()


def drain(engine, handles):
    engine.run_until_idle()
    return [h.result(timeout=0) for h in handles]


def run_trace(net, prompts, steps=6, stagger=True, **engine_kw):
    """Submit `prompts` (staggered: one step between arrivals) and drain;
    returns (engine, outputs). Every request gets rng default_rng(i)."""
    eng = GenerationEngine(net, V, **engine_kw)
    hs = []
    for i, p in enumerate(prompts):
        hs.append(eng.submit(p, steps=steps,
                             rng=np.random.default_rng(i),
                             **getattr(run_trace, "submit_kw", {})))
        if stagger:
            eng.step()
    return eng, drain(eng, hs)


# ---------------------------------------------------------------------
# parity: paged arena == one-shot sample_stream == slot arena
# ---------------------------------------------------------------------
class TestPagedParity:
    def test_greedy_staggered_matches_one_shot(self, rope_model,
                                               rope_net):
        """Mixed-length prompts through 2 slots over a small page pool
        (pages are freed and re-allocated across retirements) — every
        request bit-equal to its one-shot sample_stream run."""
        eng = GenerationEngine(rope_net, V, slots=2,
                               paging=PagedKVConfig(page_size=4))
        hs = []
        for i, p in enumerate(PROMPTS[:2]):
            hs.append(eng.submit(p, steps=7, top_k=1,
                                 rng=np.random.default_rng(i)))
        eng.step()
        eng.step()
        for i, p in enumerate(PROMPTS[2:], start=2):
            hs.append(eng.submit(p, steps=7, top_k=1,
                                 rng=np.random.default_rng(i)))
            eng.step()
        got = drain(eng, hs)
        for i, p in enumerate(PROMPTS):
            want = rope_model.sample_stream(
                rope_net, p, steps=7, top_k=1,
                rng=np.random.default_rng(i))
            assert got[i] == want, p
        # retirement freed every slot page; only cached blocks remain
        assert eng.page_pool.used_count() == len(eng.prefix_cache)

    def test_sampled_mixed_configs_match_one_shot(self, rope_model,
                                                  rope_net):
        cfgs = [dict(temperature=0.7, top_k=3),
                dict(temperature=1.2, top_p=0.9),
                dict(top_k=1),
                dict(temperature=0.9)]
        eng = GenerationEngine(rope_net, V, slots=4,
                               paging=PagedKVConfig(page_size=4))
        hs = [eng.submit([1 + i, 2, 3], steps=6,
                         rng=np.random.default_rng(10 + i), **c)
              for i, c in enumerate(cfgs)]
        got = drain(eng, hs)
        for i, c in enumerate(cfgs):
            want = rope_model.sample_stream(
                rope_net, [1 + i, 2, 3], steps=6,
                rng=np.random.default_rng(10 + i), **c)
            assert got[i] == want, c

    def test_paged_equals_slot_arena_bitwise(self, rope_net):
        """The paged gather/scatter round trip is invisible: same
        staggered sampled trace through both arenas, identical ids."""
        kw = dict(steps=6, stagger=True)
        _, slot_out = run_trace(rope_net, PROMPTS, slots=2, **kw)
        _, paged_out = run_trace(rope_net, PROMPTS, slots=2,
                                 paging=PagedKVConfig(page_size=4), **kw)
        assert paged_out == slot_out

    def test_chunked_prime_matches_too(self, rope_model, rope_net):
        eng = GenerationEngine(rope_net, V, slots=2, prime_padded=False,
                               paging=PagedKVConfig(page_size=4))
        hs = [eng.submit(p, steps=4, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:3])]
        got = drain(eng, hs)
        for i, p in enumerate(PROMPTS[:3]):
            assert got[i] == rope_model.sample_stream(
                rope_net, p, steps=4, top_k=1,
                rng=np.random.default_rng(i))


# ---------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------
class TestPrefixCache:
    def test_hit_miss_accounting(self, rope_net):
        reg = MetricsRegistry()
        prompts = [SYS + [t] for t in (2, 5, 9)] + [[9, 9, 2]]
        eng = GenerationEngine(rope_net, V, slots=4, registry=reg,
                               name="engine:pfx",
                               paging=PagedKVConfig(page_size=4))
        hs = [eng.submit(p, steps=4, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(prompts)]
        drain(eng, hs)
        # first SYS request misses and caches 2 full blocks; the next
        # two hit them; the unrelated prompt misses
        assert eng.prefix_cache.hits == 2
        assert eng.prefix_cache.misses == 2
        assert eng.prefix_cache.reused_tokens == 2 * len(SYS)
        snap = reg.snapshot_compact()
        assert snap[SERVING_PREFIX_HITS + "{model=engine:pfx}"] == 2
        assert snap[SERVING_PREFIX_MISSES + "{model=engine:pfx}"] == 2

    def test_cache_on_off_bit_exact(self, rope_net):
        """Shared AND non-shared prompts, greedy and sampled: cache-on
        outputs equal cache-off outputs bit for bit."""
        prompts = [SYS + [t] for t in (2, 5)] + [[4, 1], SYS + [9, 9]]
        for extra in (dict(), dict(temperature=0.8, top_p=0.95)):
            run_trace.submit_kw = extra
            try:
                _, off = run_trace(
                    rope_net, prompts, slots=3,
                    paging=PagedKVConfig(page_size=4,
                                         prefix_cache=False))
                eng, on = run_trace(
                    rope_net, prompts, slots=3,
                    paging=PagedKVConfig(page_size=4))
            finally:
                run_trace.submit_kw = {}
            assert on == off, extra
            assert eng.prefix_cache.hits >= 2

    def test_eviction_under_page_pressure(self, rope_model, rope_net):
        """With the pool nearly consumed by cached blocks, a new
        admission evicts LRU unmapped entries instead of head-blocking
        forever — and its output is still exact."""
        ref = rope_model.sample_stream(rope_net, [5, 3] * 6, steps=8,
                                       top_k=1,
                                       rng=np.random.default_rng(0))
        eng = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, total_pages=10))
        seeds = [[1 + i] * 9 for i in range(3)]   # 2 full blocks each
        hs = [eng.submit(p, steps=2, top_k=1) for p in seeds]
        drain(eng, hs)
        assert len(eng.prefix_cache) == 6         # 3 x 2 cached blocks
        h = eng.submit([5, 3] * 6, steps=8, top_k=1,
                       rng=np.random.default_rng(0))   # needs 5 of the
        eng.run_until_idle()                           # 4 free pages
        assert h.result(timeout=0) == ref
        # one LRU block was evicted to fit it; its own 3 full blocks
        # were then cached: 6 - 1 + 3
        assert len(eng.prefix_cache) == 8

    def test_lru_survivors_still_hit(self, rope_net):
        """Eviction keeps recently used chains: after pressure, a
        repeat of the most recent seed still hits."""
        eng = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, total_pages=12))
        a, b = [1] * 9, [2] * 9
        drain(eng, [eng.submit(p, steps=2, top_k=1) for p in (a, b)])
        drain(eng, [eng.submit(b, steps=2, top_k=1)])   # touch b
        h = eng.submit([5, 3] * 6, steps=8, top_k=1)    # forces eviction
        eng.run_until_idle()
        h.result(timeout=0)
        hits0 = eng.prefix_cache.hits
        drain(eng, [eng.submit(b + [7], steps=2, top_k=1)])
        assert eng.prefix_cache.hits > hits0

    def test_recurrent_state_rejects_prefix_cache(self):
        lstm = TextGenerationLSTM(vocab_size=10, hidden=12, layers=1,
                                  max_length=40).init()
        with pytest.raises(ValueError, match="pages"):
            GenerationEngine(lstm, 10, slots=2,
                             paging=PagedKVConfig(page_size=4))


# ---------------------------------------------------------------------
# token-budget admission (satellite: admission-time capacity bugfix)
# ---------------------------------------------------------------------
class TestPagedAdmission:
    def test_oversized_request_rejected_at_submit(self, rope_net):
        """A request whose prompt + steps can NEVER fit the page budget
        fails at submit — it is not admitted and retired mid-stream."""
        eng = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, total_pages=4))
        with pytest.raises(ValueError, match="never"):
            eng.submit([1, 2, 3, 4], steps=20, top_k=1)
        # an in-budget request on the same engine still serves
        h = eng.submit([1, 2, 3], steps=4, top_k=1)
        eng.run_until_idle()
        assert h.finish_reason == "length"

    def test_token_budget_admits_beyond_worst_case(self, rope_net):
        """Short requests hold few pages: a pool sized for TWO
        worst-case streams runs FOUR short requests concurrently."""
        eng = GenerationEngine(
            rope_net, V, slots=4,
            paging=PagedKVConfig(page_size=4, total_pages=16))
        hs = [eng.submit([1 + i, 2], steps=6, top_k=1,
                         rng=np.random.default_rng(i))
              for i in range(4)]
        eng.step()
        assert eng.active_slots() == 4     # all admitted immediately
        drain(eng, hs)

    def test_head_blocks_until_pages_free(self, rope_net):
        """A request needing more pages than are free queues (head-of-
        line) and admits as soon as retirement frees them."""
        eng = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, total_pages=8,
                                 prefix_cache=False))
        big = eng.submit([1] * 12, steps=8, top_k=1)    # 5 pages
        eng.step()
        big2 = eng.submit([2] * 12, steps=8, top_k=1)   # queues: 5 > 3
        eng.step()
        assert eng.active_slots() == 1
        assert eng.queue_depth() == 1
        drain(eng, [big, big2])
        assert big.finish_reason == "length"
        assert big2.finish_reason == "length"

    def test_pages_free_immediately_on_retirement(self, rope_net):
        eng = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, prefix_cache=False))
        h = eng.submit([1, 2, 3, 4, 5], steps=4, top_k=1)
        eng.step()
        assert eng.page_pool.used_count() > 0
        drain(eng, [h])
        assert eng.page_pool.used_count() == 0

    def test_pure_recurrent_net_rejects_paging(self):
        lstm = TextGenerationLSTM(vocab_size=10, hidden=12, layers=1,
                                  max_length=40).init()
        with pytest.raises(ValueError, match="paged"):
            GenerationEngine(lstm, 10, slots=2,
                             paging=PagedKVConfig(page_size=4,
                                                  prefix_cache=False))

    def test_windowed_cache_rejects_paging(self):
        net = TextGenerationTransformer(
            vocab_size=V, embed_dim=16, n_heads=2, n_layers=1,
            max_length=64, positional="rope", window=8).init()
        with pytest.raises(ValueError, match="rolling"):
            GenerationEngine(net, V, slots=2,
                             paging=PagedKVConfig(page_size=4))


# ---------------------------------------------------------------------
# in-engine speculation
# ---------------------------------------------------------------------
class TestSpeculation:
    def spec(self, gamma=3):
        return SpeculationConfig(draft=prompt_lookup_proposer(2),
                                 gamma=gamma)

    def test_greedy_matches_one_shot(self, rope_model, rope_net):
        """Greedy speculative outputs are the argmax chain regardless
        of acceptance pattern — bit-identical to plain sample_stream,
        on both arenas."""
        prompts = [p * 3 for p in PROMPTS[:4]]   # repetition: real hits
        ref = [rope_model.sample_stream(rope_net, p, steps=8, top_k=1,
                                        rng=np.random.default_rng(i))
               for i, p in enumerate(prompts)]
        for paging in (None, PagedKVConfig(page_size=4)):
            run_trace.submit_kw = dict(top_k=1)
            try:
                eng, got = run_trace(rope_net, prompts, steps=8,
                                     slots=2, paging=paging,
                                     speculation=self.spec())
            finally:
                run_trace.submit_kw = {}
            assert got == ref, paging
            assert eng._dispatches > 0

    def test_sampled_identical_across_arenas(self, rope_net):
        """Sampled speculation preserves the target distribution; the
        drawn SEQUENCE is additionally pinned identical across slot /
        paged / paged+prefix arenas (same per-request rngs)."""
        prompts = [p * 2 for p in PROMPTS[:3]]
        run_trace.submit_kw = dict(temperature=0.9, top_p=0.9)
        try:
            outs = [run_trace(rope_net, prompts, steps=6, slots=2,
                              paging=pg, speculation=self.spec())[1]
                    for pg in (None,
                               PagedKVConfig(page_size=4,
                                             prefix_cache=False),
                               PagedKVConfig(page_size=4))]
        finally:
            run_trace.submit_kw = {}
        assert outs[0] == outs[1] == outs[2]

    def test_stop_tokens_cut_like_one_shot(self, rope_model, rope_net):
        ref0 = rope_model.sample_stream(rope_net, PROMPTS[0] * 3,
                                        steps=10, top_k=1,
                                        rng=np.random.default_rng(0))
        stop = ref0[len(PROMPTS[0] * 3) + 1]
        eng = GenerationEngine(rope_net, V, slots=2,
                               speculation=self.spec())
        hs = [eng.submit(p * 3, steps=10, top_k=1, stop_tokens=(stop,),
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:2])]
        got = drain(eng, hs)
        for i, p in enumerate(PROMPTS[:2]):
            assert got[i] == rope_model.sample_stream(
                rope_net, p * 3, steps=10, top_k=1, stop_tokens=(stop,),
                rng=np.random.default_rng(i))

    def test_acceptance_telemetry(self, rope_net):
        reg = MetricsRegistry()
        eng = GenerationEngine(rope_net, V, slots=2, registry=reg,
                               name="engine:spec",
                               speculation=self.spec())
        hs = [eng.submit([1, 2] * 6, steps=8, top_k=1)]
        drain(eng, hs)
        snap = reg.snapshot_compact()
        hist = snap[SERVING_SPEC_ACCEPTANCE + "{model=engine:spec}"]
        assert hist["count"] > 0
        # a periodic prompt + prompt-lookup drafting must accept > 0
        assert hist["sum"] > 0

    def test_headroom_enforced_at_submit(self, rope_net):
        eng = GenerationEngine(rope_net, V, slots=2,
                               speculation=self.spec(gamma=4))
        with pytest.raises(ValueError, match="headroom"):
            eng.submit([1, 2, 3], steps=29, top_k=1)   # 32 = cap > 29
        h = eng.submit([1, 2, 3], steps=20, top_k=1)
        eng.run_until_idle()
        assert h.finish_reason == "length"

    def test_lstm_rejects_speculation(self):
        lstm = TextGenerationLSTM(vocab_size=10, hidden=12, layers=1,
                                  max_length=40).init()
        with pytest.raises(ValueError, match="rewound|recurrent"):
            GenerationEngine(lstm, 10, slots=2, speculation=self.spec())

    def test_model_draft_rejected(self, rope_net):
        with pytest.raises(TypeError, match="proposer"):
            SpeculationConfig(draft=rope_net, gamma=2)


# ---------------------------------------------------------------------
# chaos: page exhaustion degrades gracefully (satellite)
# ---------------------------------------------------------------------
class TestPageExhaustionChaos:
    def test_seized_pool_blocks_admissions_not_streams(self, rope_model,
                                                       rope_net):
        """Free pages vanish mid-flight (chaos seize at dispatch 1):
        active requests complete bit-identically to an unperturbed run;
        a request needing the seized capacity stays queued — even after
        the actives retire and return THEIR pages — until release()."""
        refs = [rope_model.sample_stream(rope_net, p, steps=6, top_k=1,
                                         rng=np.random.default_rng(i))
                for i, p in enumerate(PROMPTS[:2])]
        ref_late = rope_model.sample_stream(
            rope_net, [4, 5, 6], steps=21, top_k=1,
            rng=np.random.default_rng(9))
        eng = GenerationEngine(
            rope_net, V, slots=3,
            paging=PagedKVConfig(page_size=4, total_pages=6,
                                 prefix_cache=False))
        inj = chaos.PageExhaustionInjector(eng.page_pool, n=1,
                                           free_target=0)
        eng._decode_chaos = inj
        hs = [eng.submit(p, steps=6, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:2])]   # 3 + 2 of 6 pages
        eng.step()
        eng.step()                        # injector fires: free -> 0
        assert eng.page_pool.free_count() == 0
        late = eng.submit([4, 5, 6], steps=21, top_k=1,
                          rng=np.random.default_rng(9))   # needs all 6
        eng.step()
        assert eng.queue_depth() == 1     # head-blocked, not admitted
        got = drain(eng, hs)              # actives unaffected
        assert got == refs
        assert not late.done              # still starved after drain
        inj.release()
        eng.run_until_idle()
        assert late.result(timeout=0) == ref_late


# ---------------------------------------------------------------------
# telemetry: page gauges ride the registry
# ---------------------------------------------------------------------
class TestPagedTelemetry:
    def test_page_gauges(self, rope_net):
        reg = MetricsRegistry()
        eng = GenerationEngine(
            rope_net, V, slots=2, registry=reg, name="engine:pg",
            paging=PagedKVConfig(page_size=4, total_pages=12,
                                 prefix_cache=False))
        h = eng.submit([1, 2, 3, 4, 5], steps=6, top_k=1)
        eng.step()
        snap = reg.snapshot_compact()
        assert snap[SERVING_KV_PAGES_TOTAL + "{model=engine:pg}"] == 12
        assert snap[SERVING_KV_PAGES_USED + "{model=engine:pg}"] > 0
        drain(eng, [h])
        snap = reg.snapshot_compact()
        assert snap[SERVING_KV_PAGES_USED + "{model=engine:pg}"] == 0


# ---------------------------------------------------------------------
# acceptance: zero retraces after warmup, every mode on
# ---------------------------------------------------------------------
def _compile_total():
    c = monitoring.global_registry().get(runtime.COMPILE_COUNTER)
    return 0.0 if c is None else c.total()


class TestNoRetracePagedAfterWarmup:
    def test_staggered_paged_spec_prefix_traffic_compiles_nothing(self):
        """After warmup(), staggered mixed-length admissions — some
        sharing a system prompt (prefix hits), all speculating, pages
        recycling through retirements — hit only warm shapes."""
        monitoring.ensure_started()
        model = TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=64,
                                          positional="rope")
        net = model.init()
        eng = GenerationEngine(
            net, V, slots=4, paging=PagedKVConfig(page_size=8),
            speculation=SpeculationConfig(
                draft=prompt_lookup_proposer(2), gamma=3))
        eng.warmup(max_prompt_len=16)
        warm = _compile_total()
        rng = np.random.default_rng(0)
        hs = []
        for i in range(12):
            n = int(rng.integers(1, 16))
            p = (SYS + list(rng.integers(1, V, n - 8))
                 if i % 2 and n > 8 else list(rng.integers(1, V, n)))
            hs.append(eng.submit(p, steps=int(rng.integers(2, 10)),
                                 top_k=1, rng=np.random.default_rng(i)))
            eng.step()
        eng.run_until_idle()
        assert all(h.done for h in hs)
        assert eng.prefix_cache.hits > 0      # the hit path really ran
        assert _compile_total() == warm, (
            "paged/speculative serving retraced after warmup")
