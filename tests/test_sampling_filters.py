"""top-k / top-p (nucleus) sampling filters on the shared draw()
(util/decoding) and their passthrough on the decode entry points."""

import numpy as np
import pytest

from deeplearning4j_tpu.util.decoding import draw, filter_probs
from deeplearning4j_tpu.zoo import TextGenerationTransformer


def _probs(vals):
    p = np.asarray(vals, np.float64)
    return p / p.sum()


class TestDraw:
    def test_top_k_1_is_greedy(self):
        p = _probs([0.1, 0.5, 0.2, 0.2])
        for seed in range(5):
            assert draw(p, 2.0, np.random.default_rng(seed), top_k=1) == 1

    def test_top_k_restricts_support(self):
        p = _probs([0.4, 0.3, 0.2, 0.1])
        rng = np.random.default_rng(0)
        seen = {draw(p, 1.0, rng, top_k=2) for _ in range(200)}
        assert seen <= {0, 1}
        assert seen == {0, 1}          # both survivors actually drawn

    def test_top_p_keeps_smallest_prefix(self):
        # sorted mass: .4, .3, .2, .1 — top_p=.6 keeps {0,1} (prefix sums
        # .4, .7: first prefix reaching .6 is two tokens)
        p = _probs([0.4, 0.3, 0.2, 0.1])
        rng = np.random.default_rng(0)
        seen = {draw(p, 1.0, rng, top_p=0.6) for _ in range(200)}
        assert seen == {0, 1}

    def test_top_p_never_empty(self):
        p = _probs([0.999, 0.001, 0.0001])
        assert draw(p, 1.0, np.random.default_rng(0), top_p=0.01) == 0

    def test_filters_compose(self):
        p = _probs([0.4, 0.3, 0.2, 0.1])
        rng = np.random.default_rng(0)
        seen = {draw(p, 1.0, rng, top_k=3, top_p=0.5) for _ in range(200)}
        # top_k keeps {0,1,2} renormalized to .44/.33/.22; top_p=.5 then
        # keeps the first two (prefix sums .44, .78)
        assert seen == {0, 1}

    def test_temperature_applies_before_filtering(self):
        # temperature ~0 concentrates everything on the argmax, so even
        # a wide top_p draws only it
        p = _probs([0.3, 0.31, 0.39])
        assert draw(p, 1e-4, np.random.default_rng(0), top_p=0.99) == 2

    def test_top_k_exact_even_with_ties(self):
        """A flat (tied) tail must not survive a top_k cut: exactly k
        indices are kept, not every token tied with the kth value."""
        p = np.full(100, 1e-9)
        p[7] = 1.0
        p = p / p.sum()
        rng = np.random.default_rng(0)
        # top_k=3 on a 99-way-tied tail: draws come from only 3 tokens
        seen = {draw(p, 2.0, rng, top_k=3) for _ in range(300)}
        assert len(seen) <= 3
        assert 7 in seen

    def test_validation(self):
        p = _probs([0.5, 0.5])
        with pytest.raises(ValueError, match="top_k"):
            draw(p, 1.0, np.random.default_rng(0), top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            draw(p, 1.0, np.random.default_rng(0), top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            draw(p, 1.0, np.random.default_rng(0), top_p=1.5)


class TestEntryPoints:
    def test_sample_stream_top_k_greedy_deterministic(self):
        model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=32)
        net = model.init()
        a = model.sample_stream(net, [1, 2, 3], steps=5, top_k=1,
                                rng=np.random.default_rng(0))
        b = model.sample_stream(net, [1, 2, 3], steps=5, top_k=1,
                                rng=np.random.default_rng(99))
        assert a == b                  # greedy ignores the rng

    def test_sample_stream_top_p_runs(self):
        model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=32)
        net = model.init()
        ids = model.sample_stream(net, [1, 2, 3], steps=5, top_p=0.9,
                                  rng=np.random.default_rng(1))
        assert len(ids) == 8 and all(0 <= i < 12 for i in ids)


class TestPerRowFilters:
    """Vectorized batch filtering (filter_probs/draw over [B, V] with
    per-row temperature/top_k/top_p) == the scalar path row for row."""

    def _batch(self, B=6, V=32, seed=0):
        rng = np.random.default_rng(seed)
        p = rng.random((B, V))
        return p / p.sum(axis=-1, keepdims=True)

    def test_batch_equals_scalar_rows_shared_params(self):
        p = self._batch()
        got = filter_probs(p, 0.8, top_k=5, top_p=0.9)
        for b in range(len(p)):
            want = filter_probs(p[b], 0.8, top_k=5, top_p=0.9)
            np.testing.assert_array_equal(got[b], want)

    def test_batch_equals_scalar_rows_per_row_params(self):
        p = self._batch()
        temps = np.array([0.5, 0.8, 1.0, 1.3, 2.0, 0.7])
        ks = np.array([1, 3, 0, 8, 0, 2])      # 0 = top_k off
        ps = np.array([0.0, 0.9, 0.5, 0.0, 0.99, 1.0])  # 0 = off
        got = filter_probs(p, temps, top_k=ks, top_p=ps)
        for b in range(len(p)):
            want = filter_probs(
                p[b], float(temps[b]),
                top_k=int(ks[b]) if ks[b] > 0 else None,
                top_p=float(ps[b]) if ps[b] > 0 else None)
            np.testing.assert_array_equal(got[b], want)

    def test_per_row_off_entries_leave_row_unfiltered(self):
        p = self._batch(B=2, V=8)
        got = filter_probs(p, 1.0, top_k=np.array([2, 0]))
        assert (got[0] > 0).sum() == 2
        assert (got[1] > 0).sum() == 8

    def test_draw_batch_with_per_row_rngs(self):
        p = self._batch(B=4, V=16, seed=3)
        rngs = [np.random.default_rng(b) for b in range(4)]
        got = draw(p, 1.0, rngs, top_k=np.array([1, 4, 0, 2]))
        want = [draw(p[b], 1.0, np.random.default_rng(b),
                     top_k=[1, 4, None, 2][b])
                for b in range(4)]
        assert got == want

    def test_greedy_rows_are_argmax(self):
        p = self._batch(B=3, V=10, seed=5)
        got = draw(p, 2.0, np.random.default_rng(0), top_k=1)
        assert got == list(p.argmax(axis=-1))

    def test_batch_validation(self):
        p = self._batch(B=3, V=8)
        with pytest.raises(ValueError, match="temperature"):
            filter_probs(p, np.array([1.0, 1.0]))       # wrong length
        with pytest.raises(ValueError, match="> 0"):
            filter_probs(p, np.array([1.0, -1.0, 1.0]))
        with pytest.raises(ValueError, match="top_p"):
            filter_probs(p, 1.0, top_p=np.array([0.5, 1.5, 0.5]))
        with pytest.raises(ValueError, match="rng per row"):
            draw(p, 1.0, [np.random.default_rng(0)])
