"""bench_probe unit tests: the tunnel probe/retry loop and SIGTERM
machinery that bench.py/bench_all.py gate their jax imports on (VERDICT
r4 task 1 — a short live window must still produce a driver record, and
every failure mode must yield the one-JSON-line contract).

The real probe spawns a jax subprocess; these tests monkeypatch
probe_once/time so the loop logic is pinned without tunnel access.
"""

import importlib
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench_probe  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh():
    importlib.reload(bench_probe)
    yield


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def monotonic(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def _patch_probe(monkeypatch, results, cost=5.0):
    """probe_once returns successive entries from `results`, each
    advancing the fake clock by `cost` (or per-entry cost)."""
    clock = FakeClock()
    monkeypatch.setattr(bench_probe.time, "monotonic", clock.monotonic)
    monkeypatch.setattr(bench_probe.time, "sleep", clock.sleep)
    seq = list(results)
    calls = []

    def fake_probe(timeout=None):
        calls.append(timeout)
        item = seq.pop(0) if seq else ("", "")
        c = item[2] if len(item) > 2 else cost
        clock.t += c
        return item[0], item[1]

    monkeypatch.setattr(bench_probe, "probe_once", fake_probe)
    return clock, calls


class TestWaitForTpu:
    def test_first_probe_success(self, monkeypatch):
        _patch_probe(monkeypatch, [("tpu", "")])
        platform, attempts, waited, err = bench_probe.wait_for_tpu()
        assert platform == "tpu" and attempts == 1 and err == ""

    def test_retries_until_live(self, monkeypatch):
        monkeypatch.setattr(bench_probe, "PROBE_BUDGET", 300.0)
        clock, _ = _patch_probe(
            monkeypatch, [("", ""), ("", ""), ("tpu", "")], cost=30.0)
        platform, attempts, waited, _ = bench_probe.wait_for_tpu()
        assert platform == "tpu" and attempts == 3
        assert len(clock.sleeps) == 2   # slept between attempts only

    def test_budget_exhaustion_returns_none(self, monkeypatch):
        monkeypatch.setattr(bench_probe, "PROBE_BUDGET", 60.0)
        _patch_probe(monkeypatch, [("", "")] * 10, cost=30.0)
        platform, attempts, waited, _ = bench_probe.wait_for_tpu()
        assert platform is None
        assert attempts <= 3

    def test_wall_time_does_not_overshoot_budget(self, monkeypatch):
        """The sleep keeps at least a useful probe of budget, and the
        per-probe timeout clamps to the remainder — total wall time
        stays within budget + one clamped probe, never budget +
        PROBE_TIMEOUT."""
        monkeypatch.setattr(bench_probe, "PROBE_BUDGET", 100.0)
        monkeypatch.setattr(bench_probe, "PROBE_TIMEOUT", 70.0)
        clock, calls = _patch_probe(monkeypatch, [("", "")] * 10,
                                    cost=30.0)
        bench_probe.wait_for_tpu()
        assert clock.t <= 100.0 + bench_probe._MIN_USEFUL_PROBE
        # the remaining-budget clamp actually reached probe_once: with
        # budget 100 / cost 30 / sleep 20 the exact schedule is probe@0
        # (remaining 100 -> 70), probe@50 (remaining 50), probe@85
        # (remaining 15) — deleting the clamp would yield [70, 70, 70]
        assert calls == [70.0, 50.0, 15.0]

    def test_two_crashes_abort_early(self, monkeypatch):
        monkeypatch.setattr(bench_probe, "PROBE_BUDGET", 10_000.0)
        clock, _ = _patch_probe(
            monkeypatch,
            [("", "probe crashed rc=1: boom"),
             ("", "probe crashed rc=1: boom")], cost=5.0)
        platform, attempts, waited, err = bench_probe.wait_for_tpu()
        assert platform is None and attempts == 2
        assert "boom" in err
        assert clock.t < 60         # did not burn the huge budget

    def test_hang_resets_crash_counter(self, monkeypatch):
        """crash, hang, crash is NOT two consecutive crashes — a mix
        means the env may be flaky, keep probing."""
        monkeypatch.setattr(bench_probe, "PROBE_BUDGET", 500.0)
        _patch_probe(
            monkeypatch,
            [("", "probe crashed rc=1: x"), ("", ""),
             ("", "probe crashed rc=1: x"), ("tpu", "")], cost=20.0)
        platform, attempts, _, _ = bench_probe.wait_for_tpu()
        assert platform == "tpu" and attempts == 4


class TestProbeOnce:
    def test_crash_reports_stderr_tail(self):
        """A probe child that CRASHES (vs hangs) surfaces its stderr —
        real subprocess, broken env via a poisoned jax module."""
        import subprocess
        real_popen = subprocess.Popen

        def poisoned(cmd, **kw):
            return real_popen(
                [sys.executable, "-c",
                 "import sys; print('dies', file=sys.stderr); "
                 "sys.exit(1)"], **kw)

        orig = bench_probe.subprocess.Popen
        bench_probe.subprocess.Popen = poisoned
        try:
            platform, err = bench_probe.probe_once(timeout=30)
        finally:
            bench_probe.subprocess.Popen = orig
        assert platform == ""
        assert "crashed" in err and "dies" in err

    def test_success_parses_last_line(self):
        import subprocess
        real_popen = subprocess.Popen

        def fake(cmd, **kw):
            return real_popen(
                [sys.executable, "-c", "print('noise'); print('cpu')"],
                **kw)

        orig = bench_probe.subprocess.Popen
        bench_probe.subprocess.Popen = fake
        try:
            platform, err = bench_probe.probe_once(timeout=30)
        finally:
            bench_probe.subprocess.Popen = orig
        assert platform == "cpu" and err == ""


class TestSigtermHandler:
    def test_default_claim_single_emit(self, monkeypatch):
        import signal as signal_mod
        installed = {}
        monkeypatch.setattr(
            bench_probe.signal, "signal",
            lambda sig, h: installed.setdefault(sig, h))
        writes = []
        exits = []
        monkeypatch.setattr(bench_probe.os, "write",
                            lambda fd, b: writes.append((fd, b)))
        monkeypatch.setattr(bench_probe.os, "_exit",
                            lambda rc: exits.append(rc))
        bench_probe.install_sigterm_handler(
            lambda signum: f"killed:{signum}\n".encode())
        handler = installed[signal_mod.SIGTERM]
        handler(15, None)
        handler(15, None)     # second delivery: no second line
        assert writes == [(1, b"killed:15\n")]
        assert exits == [3, 3]

    def test_claim_none_returns_without_exit(self, monkeypatch):
        import signal as signal_mod
        installed = {}
        monkeypatch.setattr(
            bench_probe.signal, "signal",
            lambda sig, h: installed.setdefault(sig, h))
        exits = []
        monkeypatch.setattr(bench_probe.os, "_exit",
                            lambda rc: exits.append(rc))
        seen = []
        bench_probe.install_sigterm_handler(
            lambda signum: b"x\n",
            try_claim=lambda signum: seen.append(signum) or None)
        installed[signal_mod.SIGTERM](15, None)
        assert exits == [] and seen == [15]

    def test_handler_kills_inflight_probe_child(self, monkeypatch):
        import signal as signal_mod
        installed = {}
        monkeypatch.setattr(
            bench_probe.signal, "signal",
            lambda sig, h: installed.setdefault(sig, h))
        monkeypatch.setattr(bench_probe.os, "_exit", lambda rc: None)
        monkeypatch.setattr(bench_probe.os, "write", lambda fd, b: None)

        class Child:
            killed = False

            def kill(self):
                Child.killed = True

        bench_probe._probe_child = Child()
        try:
            bench_probe.install_sigterm_handler(lambda s: b"x\n")
            installed[signal_mod.SIGTERM](15, None)
        finally:
            bench_probe._probe_child = None
        assert Child.killed


class TestBenchAbPartial:
    """bench.py A/B partial preservation: a completed unfused leg must
    survive a hang/kill in the optional fused leg as a REAL record."""

    @pytest.fixture(autouse=True)
    def _bench(self):
        import bench
        importlib.reload(bench)
        self.bench = bench
        yield
        self.bench._partial.clear()

    def test_term_line_without_partial_is_failure(self):
        import json
        line = json.loads(self.bench._term_line(15).decode())
        assert line["value"] is None and line["error"] == "killed"

    def test_term_line_with_partial_carries_real_number(self):
        import json
        self.bench._partial.update(
            value=2650.0, vs=13.25, platform="tpu",
            extra={"unfused_img_s": 2650.0, "plan": "unfused"})
        line = json.loads(self.bench._term_line(15).decode())
        assert line["value"] == 2650.0
        assert line["plan"] == "unfused"
        assert "killed" in line["ab_incomplete"]

    def test_watchdog_path_emits_partial(self, capsys):
        import json
        self.bench._partial.update(
            value=2650.0, vs=13.25, platform="tpu",
            extra={"plan": "unfused"})
        emitted, had = self.bench._emit_partial_or_fail(
            "tpu-unavailable", "device hang mid-run")
        assert emitted and had
        line = json.loads(capsys.readouterr().out.strip())
        assert line["value"] == 2650.0
        assert "tpu-unavailable" in line["ab_incomplete"]

    def test_single_emission_partial_then_nothing(self, capsys):
        self.bench._partial.update(value=1.0, vs=0.005, platform="tpu",
                                   extra={})
        assert self.bench._emit_partial_or_fail("x", "y")[0]
        assert not self.bench._emit_partial_or_fail("x", "y")[0]
        assert len(capsys.readouterr().out.strip().splitlines()) == 1
