"""tpulint (deeplearning4j_tpu/analysis): per-rule positive/negative
fixtures, inline suppressions, baseline round-trip, CLI contract, and the
self-scan gate that keeps the repo clean beyond the committed baseline."""

import json
import os
import textwrap
from pathlib import Path

import pytest

from deeplearning4j_tpu.analysis import baseline as bl
from deeplearning4j_tpu.analysis.cli import main
from deeplearning4j_tpu.analysis.core import scan_file, scan_paths
from deeplearning4j_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "deeplearning4j_tpu"


def _scan_snippet(tmp_path, source, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return scan_file(str(p), ALL_RULES, root=str(tmp_path))


def _rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------
# rule: host-sync-in-hot-loop
# ---------------------------------------------------------------------
class TestHostSyncRule:
    def test_positive_float_and_block_in_per_batch_path(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class Net:
                def _fit_batch(self, ds):
                    loss = self.step(ds)
                    self.score = float(loss)
                    jax.block_until_ready(self.params)
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"] * 2

    def test_positive_item_and_device_get_in_fit_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def fit(model, batches):
                for b in batches:
                    loss = model.step(b)
                    print(loss.item())
                    jax.device_get(loss)
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"] * 2

    def test_negative_outside_hot_path_or_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def fit(model, b):
                loss = model.step(b)      # no loop at this level
                return float(loss)

            def score(model, b):
                return float(model.loss(b))
        """)
        assert fs == []

    def test_negative_module_without_jax_is_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import numpy as np

            def fit(stats, batches):
                for b in batches:
                    stats.append(float(np.mean(b)))
        """)
        assert fs == []

    def test_negative_benign_scalar_casts_and_host_literals(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import numpy as np

            def _fit_batch(self, ds, seqs):
                n = int(ds.features.shape[0])
                m = float(len(seqs))
                lens = np.asarray([len(s) for s in seqs])
                return n, m, lens
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: device-transfer-in-hot-loop
# ---------------------------------------------------------------------
class TestDeviceTransferRule:
    def test_positive_asarray_and_device_put_in_per_batch_path(self,
                                                               tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Net:
                def _fit_batch(self, ds):
                    x = jnp.asarray(ds.features)
                    y = jax.device_put(ds.labels)
                    return self.step(x, y)
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"] * 2

    def test_positive_jnp_array_in_fit_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def fit(model, batches):
                for b in batches:
                    model.step(jnp.array(b.features))
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"]

    def test_negative_outside_hot_path_and_constants(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            def prepare(ds):
                # not a fit/epoch hot path: staging here is fine
                return jnp.asarray(ds.features)

            class Net:
                def _fit_batch(self, ds):
                    pad = jnp.asarray(3)  # literal scalar, not a batch
                    return self.step(ds, pad)

            def fit(model, x):
                x = jax.device_put(x)  # once, before the loop
                for _ in range(3):
                    model.step(x)
        """)
        assert fs == []

    def test_negative_module_without_jax_is_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def _fit_batch(self, ds):
                return jnp.asarray(ds.features)
        """)
        assert fs == []

    def test_suppression_and_baseline_cover_jit_boundary_remnants(
            self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            class Net:
                def _fit_batch(self, ds):
                    # compat path when prefetch is off
                    # tpulint: disable=device-transfer-in-hot-loop
                    x = jnp.asarray(ds.features)
                    return self.step(x)
        """)
        assert fs == []

    def test_positive_per_step_table_rebuild(self, tmp_path):
        """The serving decode-loop shape this rule grew to catch: the
        host rebuilds and re-uploads the full page table every step
        even when nothing changed."""
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Engine:
                def _dispatch_step(self):
                    table = jnp.asarray(self._tables_np())
                    return self._decode(self.pool[table])

                def step(self):
                    t = jax.device_put(self._tables_np())
                    return self._decode(t)
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"] * 2
        assert any("per-step path" in f.message for f in fs)

    def test_negative_cached_table_path(self, tmp_path):
        """The engine's cached-table fix shape: the transfer lives in a
        cache helper OUTSIDE the per-step names, rebuilt only after an
        invalidating mutation — steady-state steps re-upload nothing."""
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            class Engine:
                def _tables_dev(self):
                    if self._cache is None:
                        self._cache = jnp.asarray(self._tables_np())
                    return self._cache

                def _invalidate_tables(self):
                    self._cache = None

                def _dispatch_step(self):
                    return self._decode(self.pool[self._tables_dev()])
        """)
        assert fs == []

    def test_negative_nested_step_is_jit_body(self, tmp_path):
        """A nested ``def step(...)`` is a jitted/scan body — its
        jnp.asarray is a trace-time constant, not a per-step H2D."""
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Net:
                def _get_train_step(self):
                    def step(params, batch):
                        decay = jnp.asarray(self.decay_schedule)
                        return params, decay
                    return jax.jit(step)
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: tracer-leak
# ---------------------------------------------------------------------
class TestTracerLeakRule:
    def test_positive_self_assign_in_decorated_jit(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class M:
                @jax.jit
                def step(self, x):
                    self.cache = x * 2
                    return x
        """)
        assert _rules_of(fs) == ["tracer-leak"]

    def test_positive_global_assign_in_wrapped_fn(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            _LAST = None

            def step(x):
                global _LAST
                _LAST = x * 2
                return x

            fast_step = jax.jit(step)
        """)
        assert _rules_of(fs) == ["tracer-leak"]

    def test_negative_unjitted_function_may_mutate(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class M:
                def record(self, x):
                    self.cache = x * 2
                    return x
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: recompile-hazard
# ---------------------------------------------------------------------
class TestRecompileHazardRule:
    def test_positive_jit_in_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def run(fns, x):
                for f in fns:
                    y = jax.jit(f)(x)
                return y
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_positive_list_static_argnums(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def f(x, n):
                return x * n

            g = jax.jit(f, static_argnums=[1])
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_positive_branch_on_traced_arg(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_negative_static_arg_branch_and_none_check(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("train",))
            def f(x, mask, train):
                if train:                 # static: fine
                    x = x * 2
                if mask is None:          # identity check: fine
                    return x
                if x.shape[0] > 4:        # shape metadata: fine
                    return x + 1
                return x
        """)
        assert fs == []

    def test_negative_cached_jit_outside_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def get_step(cache, fn):
                if "step" not in cache:
                    cache["step"] = jax.jit(fn, static_argnums=(2,))
                return cache["step"]
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: jit-key-drift (ISSUE 13 — generalizes PR 11's env-read case)
# ---------------------------------------------------------------------
def _scan_project(tmp_path, files, rules=None):
    """Write a multi-module fixture project and scan it whole-program
    (ProjectInfo built over the directory)."""
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return scan_paths([str(tmp_path)], rules=rules, root=str(tmp_path))


class TestJitKeyDriftRule:
    def test_positive_env_read_in_jit_building_step_builder(
            self, tmp_path):
        """ISSUE 11 (migrated from recompile-hazard): os.environ
        resolved inside a step-builder body — the value bakes into the
        trace but sits in no jit key, so a flip keeps the stale
        compiled step (the BENCH_FUSE class)."""
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            class Net:
                def _get_train_step(self, carry):
                    fused = os.environ.get("MY_FUSE") == "1"

                    def step(p, x):
                        return p * x if fused else p + x

                    return jax.jit(step)
        """)
        assert _rules_of(fs) == ["jit-key-drift"]
        assert "os.environ read inside step-builder" in fs[0].message

    def test_positive_env_read_in_plan_resolution_name(self, tmp_path):
        """Name-matched plan-resolution seams are flagged even when the
        jit construction lives in a helper they call."""
        fs = _scan_snippet(tmp_path, """
            import os

            def resolve_plan(net):
                return os.getenv("MY_PLAN", "xla")
        """)
        assert _rules_of(fs) == ["jit-key-drift"]

    def test_positive_env_subscript_in_step_builder(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            def _get_output_fn(net):
                impl = os.environ["MY_IMPL"]
                return jax.jit(lambda x: x)
        """)
        assert _rules_of(fs) == ["jit-key-drift"]

    def test_negative_env_read_outside_builders(self, tmp_path):
        """Env reads at module scope or in ordinary config functions are
        someone else's business — only trace-building bodies retrace."""
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            DEFAULT_DIR = os.environ.get("MY_DATA_DIR", "/tmp")

            def load_config():
                return os.environ.get("MY_MODE", "prod")

            def get_step(cache, fn):
                return jax.jit(fn)
        """)
        assert fs == []

    def test_positive_mutable_global_unkeyed(self, tmp_path):
        """A set_*-seam module global read in a jit-building body
        without entering the cache key: the trace bakes it in."""
        fs = _scan_snippet(tmp_path, """
            import jax

            _IMPL = "xla"

            def set_impl(v):
                global _IMPL
                _IMPL = v

            def build_step(net):
                impl = _IMPL
                def step(p):
                    return p if impl == "xla" else -p
                return jax.jit(step)
        """)
        assert _rules_of(fs) == ["jit-key-drift"]
        assert "mutable global" in fs[0].message

    def test_negative_mutable_global_in_cache_key(self, tmp_path):
        """The sanctioned pattern (the repo's _STREAM_CACHE_SHARDING /
        _PAGED_DECODE_IMPL idiom): the read lands in the jit cache key,
        so flipping the seam retraces instead of staling."""
        fs = _scan_snippet(tmp_path, """
            import jax

            _IMPL = "xla"

            def set_impl(v):
                global _IMPL
                _IMPL = v

            def build_step(net, cache):
                key = ("step", _IMPL)
                if key not in cache:
                    impl = _IMPL  # same global, keyed above: exempt
                    def step(p):
                        return p if impl == "xla" else -p
                    cache[key] = jax.jit(step)
                return cache[key]
        """)
        assert fs == []

    def test_negative_immutable_global_is_config(self, tmp_path):
        """A module constant nobody rebinds via ``global`` is
        configuration, not process-wide mutable state."""
        fs = _scan_snippet(tmp_path, """
            import jax

            _DEFAULT = "xla"

            def build_step(net):
                impl = _DEFAULT
                def step(p):
                    return p if impl == "xla" else -p
                return jax.jit(step)
        """)
        assert fs == []

    def test_positive_cross_module_accessor(self, tmp_path):
        """A builder calling another module's accessor over a mutable
        global: flagged through the project layer."""
        fs = _scan_project(tmp_path, {
            "seam.py": """
                _IMPL = ("xla", False)

                def set_impl(v):
                    global _IMPL
                    _IMPL = (v, False)

                def impl():
                    return _IMPL
            """,
            "net.py": """
                import jax
                from seam import impl

                def _get_decode_fn(net):
                    mode = impl()
                    def step(x):
                        return x
                    return jax.jit(step)
            """,
        })
        assert _rules_of(fs) == ["jit-key-drift"]
        assert "accessor 'impl()'" in fs[0].message

    def test_negative_cross_module_accessor_keyed(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "seam.py": """
                _IMPL = ("xla", False)

                def set_impl(v):
                    global _IMPL
                    _IMPL = (v, False)

                def impl():
                    return _IMPL
            """,
            "net.py": """
                import jax
                from seam import impl

                def _get_decode_fn(net, cache):
                    key = ("decode", impl())
                    if key not in cache:
                        cache[key] = jax.jit(lambda x: x)
                    return cache[key]
            """,
        })
        assert fs == []

    def test_positive_construction_snapshot(self, tmp_path):
        """The PR 10 health-accounting shape: __init__ snapshots a
        process-wide accessor onto self while dispatches follow the
        LIVE setting."""
        fs = _scan_project(tmp_path, {
            "seam.py": """
                _IMPL = "xla"

                def set_impl(v):
                    global _IMPL
                    _IMPL = v

                def impl():
                    return _IMPL
            """,
            "engine.py": """
                from seam import impl

                class Engine:
                    def __init__(self):
                        self._impl = impl()
            """,
        })
        assert _rules_of(fs) == ["jit-key-drift"]
        assert "construction-time snapshot" in fs[0].message

    def test_negative_snapshot_in_owning_module_and_set_call(
            self, tmp_path):
        """The seam's own module wiring its default, and a WRITE through
        the set_* seam, are the documented pattern."""
        fs = _scan_project(tmp_path, {
            "seam.py": """
                _IMPL = "xla"

                def set_impl(v):
                    global _IMPL
                    _IMPL = v

                def impl():
                    return _IMPL

                class Local:
                    def __init__(self):
                        self._impl = impl()
            """,
            "engine.py": """
                from seam import set_impl

                class Engine:
                    def __init__(self, impl_name):
                        set_impl(impl_name)
                        self._impl = impl_name
            """,
        })
        assert fs == []


# ---------------------------------------------------------------------
# rule: donation-use-after-consume (ISSUE 13 — the PR 10 class)
# ---------------------------------------------------------------------
class TestDonationRule:
    DONATING = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x
    """

    def test_positive_read_after_donate(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x):
                out = step(state, x)
                return state + out
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]
        assert "'state'" in fs[0].message

    def test_positive_redispatch_after_donate(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x):
                a = step(state, x)
                b = step(state, x)
                return a, b
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_positive_self_attr_chain(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            class Net:
                def run(self, x):
                    out = step(self._state, x)
                    return self._state
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_positive_use_on_unreassigned_branch(self, tmp_path):
        # the else path reaches the read with the buffer consumed:
        # "any non-reassigned path" is the contract
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x, cond):
                out = step(state, x)
                if cond:
                    state = out
                return state
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_negative_reassigned_from_result(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x):
                state = step(state, x)
                return state

            def run_loop(state, xs):
                for x in xs:
                    state = step(state, x)
                return state
        """)
        assert fs == []

    def test_negative_killed_on_all_paths(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x, cond):
                out = step(state, x)
                if cond:
                    state = out
                else:
                    state = out * 2
                return state
        """)
        assert fs == []

    def test_positive_loop_redispatch_without_rebind(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, xs):
                for x in xs:
                    out = step(state, x)
                return out
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]
        assert "next loop iteration" in fs[0].message

    def test_positive_retry_shape_pr10_regression(self, tmp_path):
        """The minimized PR 10 decode_retry bug: a donate_state=True
        dispatch inside the retried callable — a retried attempt re-runs
        against consumed buffers. The fix shape (engine._donate) is
        donation OFF whenever a retry policy is configured."""
        fs = _scan_snippet(tmp_path, """
            import jax
            from mylib.retry import retry_call

            class Engine:
                def _dispatch_step(self, toks):
                    def once():
                        return self.net.rnn_time_step(
                            toks, donate_state=True)
                    return retry_call(once, policy=self._decode_retry)
        """)
        assert "donation-use-after-consume" in _rules_of(fs)
        f = [x for x in fs if x.rule == "donation-use-after-consume"][0]
        assert "retried" in f.message and "decode_retry" in f.message
        assert f.chain  # callee chain rides into --json

    def test_positive_retry_shape_donate_argnums_lambda(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x, retry_call, policy):
                return retry_call(lambda: step(state, x), policy)
        """)
        assert "donation-use-after-consume" in _rules_of(fs)

    def test_negative_retry_without_donation(self, tmp_path):
        """The FIXED engine shape: donation resolved off when a retry
        policy exists (donate_state is a non-literal expression), so
        the retried callable consumes nothing."""
        fs = _scan_snippet(tmp_path, """
            import jax
            from mylib.retry import retry_call

            class Engine:
                def _dispatch_step(self, toks):
                    def once():
                        return self.net.rnn_time_step(
                            toks, donate_state=self._donate)
                    return retry_call(once, policy=self._decode_retry)
        """)
        assert fs == []

    def test_cross_module_donating_jit(self, tmp_path):
        """Import-alias resolution: the donating jit lives in another
        module (the serving/paging.py scatter_pages shape)."""
        fs = _scan_project(tmp_path, {
            "paging.py": """
                import jax
                from functools import partial

                @partial(jax.jit, donate_argnums=(0,))
                def scatter(pool, dense):
                    return pool + dense
            """,
            "engine.py": """
                import jax
                from paging import scatter

                def commit(pool, dense):
                    out = scatter(pool, dense)
                    return pool
            """,
        })
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_negative_same_named_nested_def_not_donating(self, tmp_path):
        """A plain nested ``def step`` in one function must not inherit
        donation from an unrelated function's donating nested ``step``
        (function-local scoping of the donation map)."""
        fs = _scan_snippet(tmp_path, """
            import jax
            from functools import partial

            def builder():
                @partial(jax.jit, donate_argnums=(0,))
                def step(state, x):
                    return state + x
                return step

            def other(state, xs):
                def step(s, x):
                    return s
                out = step(state, xs)
                return state
        """)
        assert fs == []

    def test_positive_nested_donating_def_in_own_scope(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            from functools import partial

            def run(state, x):
                @partial(jax.jit, donate_argnums=(0,))
                def step(s, v):
                    return s + v
                out = step(state, x)
                return state
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_negative_try_except_rebuild_kills(self, tmp_path):
        """A reassignment inside try whose handler cannot fall through
        (bare raise) kills on every continuing path — the repo's
        recovery-path shape."""
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x, rebuild):
                out = step(state, x)
                try:
                    state = rebuild(out)
                except Exception:
                    raise
                return state
        """)
        assert fs == []

    def test_positive_try_handler_falls_through_unkilled(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.DONATING + """
            def run(state, x, rebuild, log):
                out = step(state, x)
                try:
                    state = rebuild(out)
                except Exception:
                    log("rebuild failed")
                return state
        """)
        assert _rules_of(fs) == ["donation-use-after-consume"]

    def test_negative_same_named_plain_method_not_donating(self,
                                                           tmp_path):
        """A plain B.step must not inherit donation from an unrelated
        donating A.step through a bare-name collision (class members
        are keyed Class.name only)."""
        fs = _scan_project(tmp_path, {
            "lib.py": """
                import jax
                from functools import partial

                class A:
                    @partial(jax.jit, donate_argnums=(0,))
                    def step(state, x):
                        return state + x

                class B:
                    def step(self, b, state):
                        return b
            """,
            "use.py": """
                import jax
                from lib import B

                def run(b, state):
                    out = B.step(b, state)
                    return b
            """,
        })
        assert fs == []

    def test_negative_module_assigned_wrapper_refresh(self, tmp_path):
        """``g = jax.jit(f, donate_argnums=...)`` binding form + the
        refresh idiom stays clean."""
        fs = _scan_snippet(tmp_path, """
            import jax

            def _upd(opt, grads):
                return opt

            fast_upd = jax.jit(_upd, donate_argnums=(0,))

            def run(opt, grads):
                opt = fast_upd(opt, grads)
                return opt
        """)
        assert fs == []


# ---------------------------------------------------------------------
# ProjectInfo / CallGraph (ISSUE 13 tentpole plumbing)
# ---------------------------------------------------------------------
class TestProjectInfo:
    def _build(self, tmp_path, files):
        from deeplearning4j_tpu.analysis.project import ProjectInfo
        for name, src in files.items():
            p = tmp_path / name
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        return ProjectInfo.build([str(tmp_path)], root=str(tmp_path))

    def test_module_naming_and_packages(self, tmp_path):
        proj = self._build(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/sub/__init__.py": "",
            "pkg/sub/mod.py": "def f():\n    return 1\n",
            "top.py": "X = 1\n",
        })
        assert set(proj.modules) == {"pkg", "pkg.sub", "pkg.sub.mod",
                                     "top"}
        assert proj.resolve_name("pkg.sub.mod.f") == ("pkg.sub.mod", "f")

    def test_cross_module_alias_resolution(self, tmp_path):
        proj = self._build(tmp_path, {
            "b.py": "def helper(x):\n    return x\n",
            "a.py": "import b as bee\n\ndef g(x):\n"
                    "    return bee.helper(x)\n",
        })
        mod = proj.modules["a"]
        import ast as _ast
        call = next(n for n in _ast.walk(mod.tree)
                    if isinstance(n, _ast.Call))
        assert proj.resolve_call(mod, call) == ("b", "helper")

    def test_reexport_chain_resolution(self, tmp_path):
        proj = self._build(tmp_path, {
            "b.py": "def helper(x):\n    return x\n",
            "c.py": "from b import helper\n",
            "a.py": "from c import helper\n\ndef g(x):\n"
                    "    return helper(x)\n",
        })
        assert proj.resolve_name("c.helper") == ("b", "helper")
        mod = proj.modules["a"]
        import ast as _ast
        call = next(n for n in _ast.walk(mod.tree)
                    if isinstance(n, _ast.Call))
        assert proj.resolve_call(mod, call) == ("b", "helper")

    def test_reexport_cycle_is_bounded(self, tmp_path):
        proj = self._build(tmp_path, {
            "a.py": "from b import thing\n",
            "b.py": "from a import thing\n",
        })
        assert proj.resolve_name("a.thing") is None  # no hang, no def

    def test_import_graph(self, tmp_path):
        proj = self._build(tmp_path, {
            "a.py": "import b\nimport os\n",
            "b.py": "import c\n",
            "c.py": "",
        })
        g = proj.import_graph()
        assert g["a"] == {"b"} and g["b"] == {"c"} and g["c"] == set()


class TestCallGraph:
    def _graph(self, tmp_path, files):
        from deeplearning4j_tpu.analysis.project import ProjectInfo
        for name, src in files.items():
            (tmp_path / name).write_text(textwrap.dedent(src))
        proj = ProjectInfo.build([str(tmp_path)], root=str(tmp_path))
        return proj.callgraph

    def test_direct_effect_summary(self, tmp_path):
        cg = self._graph(tmp_path, {"m.py": """
            import jax

            def helper(x):
                return jax.device_get(x)
        """})
        ev = cg.reaches("m:helper", frozenset({"host_sync"}))
        assert ev is not None
        effect, chain = ev
        assert effect.what == "jax.device_get()" and chain == ("m:helper",)

    def test_bounded_depth_cutoff(self, tmp_path):
        src = """
            import jax

            def h1(x):
                return h2(x)

            def h2(x):
                return h3(x)

            def h3(x):
                return h4(x)

            def h4(x):
                return jax.device_get(x)
        """
        cg = self._graph(tmp_path, {"m.py": src})
        # h2 -> h3 -> h4: three hops, within the bound
        assert cg.reaches("m:h2", frozenset({"host_sync"})) is not None
        # h1 -> h2 -> h3 -> h4: four hops, beyond MAX_DEPTH=3
        assert cg.reaches("m:h1", frozenset({"host_sync"})) is None

    def test_cycle_between_modules_terminates(self, tmp_path):
        cg = self._graph(tmp_path, {
            "a.py": """
                import jax
                import b

                def fa(x):
                    return b.fb(x)
            """,
            "b.py": """
                import jax
                import a

                def fb(x):
                    a.fa(x)
                    return jax.device_get(x)
            """,
        })
        ev = cg.reaches("a:fa", frozenset({"host_sync"}))
        assert ev is not None and ev[1] == ("a:fa", "b:fb")

    def test_callee_suppression_kills_propagation(self, tmp_path):
        cg = self._graph(tmp_path, {"m.py": """
            import jax

            def helper(x):
                # contract: the ONE sanctioned end-of-fit barrier
                # tpulint: disable=host-sync-in-hot-loop
                return jax.device_get(x)
        """})
        assert cg.reaches("m:helper", frozenset({"host_sync"})) is None

    def test_memo_guarded_transfer_not_an_effect(self, tmp_path):
        """The cached-table idiom: a transfer behind an ``is None``
        memo guard runs once per invalidation, not per call."""
        cg = self._graph(tmp_path, {"m.py": """
            import jax.numpy as jnp

            class E:
                def tables(self):
                    if self._cache is None:
                        self._cache = jnp.asarray(self._np())
                    return self._cache

                def fresh(self):
                    return jnp.asarray(self._np())
        """})
        assert cg.reaches("m:E.tables",
                          frozenset({"device_transfer"})) is None
        assert cg.reaches("m:E.fresh",
                          frozenset({"device_transfer"})) is not None


# ---------------------------------------------------------------------
# interprocedural promotion of the hot-loop rules (ISSUE 13 tentpole)
# ---------------------------------------------------------------------
class TestInterproceduralHostSync:
    def test_helper_sync_flagged_at_call_site_with_chain(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "util.py": """
                import jax

                def materialize(x):
                    return jax.device_get(x)
            """,
            "net.py": """
                import jax
                from util import materialize

                def fit(model, batches):
                    for b in batches:
                        loss = model.step(b)
                        materialize(loss)
            """,
        })
        assert _rules_of(fs) == ["host-sync-in-hot-loop"]
        f = fs[0]
        assert f.path == "net.py" and "materialize" in f.message
        assert f.chain and "util.py" in f.chain[-1]

    def test_two_hop_chain_through_self_method(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "net.py": """
                import jax

                class Net:
                    def _materialize(self, x):
                        return jax.device_get(x)

                    def _publish(self, x):
                        return self._materialize(x)

                    def _fit_batch(self, ds):
                        loss = self.step(ds)
                        self._publish(loss)
            """,
        })
        assert _rules_of(fs) == ["host-sync-in-hot-loop"]
        assert "Net._publish" in fs[0].message \
            and "Net._materialize" in fs[0].message

    def test_negative_clean_helper_and_cold_call_site(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "util.py": """
                import jax

                def shapes(x):
                    return x.shape

                def materialize(x):
                    return jax.device_get(x)
            """,
            "net.py": """
                import jax
                from util import materialize, shapes

                def fit(model, batches):
                    for b in batches:
                        shapes(b)          # clean helper: no finding
                    return materialize(model.params)  # after the loop
            """,
        })
        assert fs == []

    def test_negative_hot_named_callee_not_doubled(self, tmp_path):
        """A helper that is itself hot-named gets its own body finding;
        the call site must not add a second one."""
        fs = _scan_project(tmp_path, {
            "net.py": """
                import jax

                class Net:
                    def _fit_batch(self, ds):
                        return float(self.step(ds))

                    def fit(self, batches):
                        for b in batches:
                            self._fit_batch(b)
            """,
        })
        assert _rules_of(fs) == ["host-sync-in-hot-loop"]
        assert fs[0].line != 0 and "float()" in fs[0].message

    def test_callee_suppression_covers_every_caller(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "util.py": """
                import jax

                def cadence_flush(x):
                    # sanctioned: runs every N batches by contract
                    # tpulint: disable=host-sync-in-hot-loop
                    return jax.device_get(x)
            """,
            "net.py": """
                import jax
                from util import cadence_flush

                def fit(model, batches):
                    for b in batches:
                        cadence_flush(model.score)
            """,
        })
        assert fs == []


class TestInterproceduralDeviceTransfer:
    def test_helper_transfer_flagged_at_call_site(self, tmp_path):
        fs = _scan_project(tmp_path, {
            "stage.py": """
                import jax
                import jax.numpy as jnp

                def to_device(x):
                    return jnp.asarray(x)
            """,
            "net.py": """
                import jax
                from stage import to_device

                class Net:
                    def _fit_batch(self, ds):
                        x = to_device(ds.features)
                        return self.step(x)
            """,
        })
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"]
        assert "to_device" in fs[0].message and fs[0].chain

    def test_negative_memo_guarded_cache_helper(self, tmp_path):
        """The engine's cached-table shape: the helper's transfer sits
        behind an is-None memo guard — steady-state calls are free."""
        fs = _scan_project(tmp_path, {
            "net.py": """
                import jax
                import jax.numpy as jnp

                class Engine:
                    def _tables_dev(self):
                        if self._cache is None:
                            self._cache = jnp.asarray(self._np())
                        return self._cache

                    def _dispatch_step(self):
                        return self._decode(self._tables_dev())
            """,
        })
        assert fs == []


# ---------------------------------------------------------------------
# rule: dtype-promotion
# ---------------------------------------------------------------------
class TestDtypePromotionRule:
    def test_positive_np_float64_in_jax_module(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp
            import numpy as np

            def prep(x):
                return jnp.asarray(np.asarray(x, np.float64))
        """)
        assert _rules_of(fs) == ["dtype-promotion"]

    def test_positive_enable_x64_outside_shim(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            jax.config.update("jax_enable_x64", True)
        """)
        assert _rules_of(fs) == ["dtype-promotion"]

    def test_negative_no_jax_import(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import numpy as np

            def stats(x):
                return np.asarray(x, np.float64).mean()
        """)
        assert fs == []

    def test_negative_gradient_check_module_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp
            import numpy as np

            def check(p):
                return jnp.asarray(p, jnp.float64)
        """, name="gradient_check.py")
        assert fs == []


# ---------------------------------------------------------------------
# rule: int8-promotion-in-dispatch (ISSUE 18)
# ---------------------------------------------------------------------
class TestInt8PromotionRule:
    def test_positive_binop_on_int8_local(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def dequant(x, sigma):
                q = x.astype(jnp.int8)
                return q * sigma
        """)
        assert _rules_of(fs) == ["int8-promotion-in-dispatch"]
        assert "'q'" in fs[0].message

    def test_positive_int8_into_dot(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def score(q, k_ref):
                kq = jnp.asarray(k_ref, dtype=jnp.int8)
                return jnp.dot(q, kq)
        """)
        assert _rules_of(fs) == ["int8-promotion-in-dispatch"]
        assert "dot" in fs[0].message

    def test_negative_explicit_widen_before_math(self, tmp_path):
        """The quant-kernel contract shape: every int8 read widens
        through .astype before touching arithmetic."""
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def dequant(x, sigma):
                q = x.astype(jnp.int8)
                return q.astype(jnp.float32) * sigma
        """)
        assert fs == []

    def test_negative_rebinding_clears_the_taint(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def roundtrip(x, sigma):
                q = x.astype(jnp.int8)
                q = q.astype(jnp.float32)
                return q * sigma
        """)
        assert fs == []

    def test_negative_no_jax_import(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import numpy as np

            def pack(x):
                q = x.astype(np.int8)
                return q * 2
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: unlocked-thread-state
# ---------------------------------------------------------------------
class TestThreadSharedStateRule:
    def test_positive_unlocked_self_mutation_in_target(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading

            class Server:
                def start(self):
                    self._t = threading.Thread(target=self._loop)
                    self._t.start()

                def _loop(self):
                    self.count = 0
                    while True:
                        self.count += 1
        """)
        assert _rules_of(fs) == ["unlocked-thread-state"] * 2

    def test_negative_mutation_under_lock(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading

            class Server:
                def start(self):
                    self._lock = threading.Lock()
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    while True:
                        with self._lock:
                            self.count = 1
        """)
        assert fs == []

    def test_negative_queue_handoff_untouched(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import queue
            import threading

            class Server:
                def start(self):
                    self.q = queue.Queue()
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    while True:
                        item = self.q.get()
                        item.event.set()
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rules: hygiene
# ---------------------------------------------------------------------
class TestHygieneRules:
    def test_positive_bare_except_and_mutable_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def load(path, cache={}):
                try:
                    return cache[path]
                except:
                    return None
        """)
        assert _rules_of(fs) == ["bare-except", "mutable-default-arg"]

    def test_negative_typed_except_and_none_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def load(path, cache=None):
                try:
                    return (cache or {})[path]
                except KeyError:
                    return None
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: lock-held-across-dispatch
# ---------------------------------------------------------------------
class TestLockHeldAcrossDispatchRule:
    def test_positive_jitted_and_syncs_under_lock(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax
            from functools import partial

            @jax.jit
            def _dispatch(x):
                return x + 1

            @partial(jax.jit, donate_argnums=(0,))
            def _donate(x):
                return x * 2

            class Engine:
                def step(self, x, scratch):
                    with self._lock:
                        y = _dispatch(x)
                        z = _donate(scratch)  # scratch never reused
                        w = self.net.rnn_time_step(x)
                        jax.device_get(y)
                        y.block_until_ready()
                    return y
        """)
        assert _rules_of(fs) == ["lock-held-across-dispatch"] * 5

    def test_positive_known_dispatch_helpers(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            from deeplearning4j_tpu.util.decoding import step_tokens
            from deeplearning4j_tpu.serving.paging import gather_pages

            class Engine:
                def step(self, toks):
                    with self._lock:
                        view = gather_pages(self.pools, self.table,
                                            length=8)
                        return step_tokens(self.net, toks, 12)
        """)
        assert _rules_of(fs) == ["lock-held-across-dispatch"] * 2

    def test_negative_snapshot_under_lock_dispatch_outside(self,
                                                           tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Engine:
                def step(self, x):
                    with self._lock:
                        snap = dict(self.state)   # host-only under lock
                    return _dispatch(snap)        # dispatch outside
        """)
        assert fs == []

    def test_negative_condition_wait_is_the_queue_idiom(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Q:
                def pop(self, x):
                    with self._cond:
                        self._cond.wait(0.1)
                        return _dispatch(x)       # cond, not a lock
        """)
        assert fs == []

    def test_negative_lock_in_outer_function_not_this_scope(self,
                                                            tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            def outer(self, x):
                with self._lock:
                    def cb():
                        return _dispatch(x)       # runs LATER, unlocked
                    self.cb = cb
        """)
        assert fs == []

    def test_negative_lambda_defined_under_lock_runs_later(self,
                                                           tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            def outer(self, x):
                with self._lock:
                    self.cb = lambda: _dispatch(x)  # deferred, unlocked
        """)
        assert fs == []

    def test_inline_suppression(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Engine:
                def step(self, x):
                    with self._lock:
                        # single-threaded dispatcher: submit/health
                        # read lock-free, so only step() waits here
                        # tpulint: disable=lock-held-across-dispatch
                        return _dispatch(x)
        """)
        assert fs == []

    def test_repo_serving_parallel_hot_paths_are_clean(self):
        """The serving engine keeps submit/health/metrics OFF its step
        lock and its dispatches behind method seams that snapshot
        first; the repo carries no lexical lock-held dispatch (any
        future justified hold must carry an inline suppression)."""
        from deeplearning4j_tpu.analysis.rules.lock_dispatch import (
            LockHeldAcrossDispatchRule)
        fs = scan_paths([str(PKG / "serving"), str(PKG / "parallel"),
                         str(PKG / "nn"), str(PKG / "pipeline")],
                        [LockHeldAcrossDispatchRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: unbounded-retry
# ---------------------------------------------------------------------
class TestUnboundedRetryRule:
    def test_positive_while_true_sleep_swallowing_except(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def poll(fetch):
                while True:
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(1.0)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_positive_sleep_outside_handler_still_counts(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def wait_for(ready):
                while True:
                    try:
                        if ready():
                            return
                    except OSError:
                        pass
                    time.sleep(0.5)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_negative_bounded_attempts_via_raise(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def fetch_with_cap(fetch, limit=5):
                attempts = 0
                while True:
                    try:
                        return fetch()
                    except ConnectionError:
                        attempts += 1
                        if attempts >= limit:
                            raise
                        time.sleep(0.1 * attempts)
        """)
        assert fs == []

    def test_negative_for_range_and_condition_loops(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def bounded(fetch):
                for attempt in range(5):
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(0.1)

            def stoppable(fetch, stop):
                while not stop.is_set():
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(0.1)
        """)
        assert fs == []

    def test_positive_nested_escape_does_not_bound(self, tmp_path):
        # the break exits only the inner for, the return lives in a
        # nested def, and the raise is swallowed by an inner try: none
        # of them bounds the retry — still unbounded
        fs = _scan_snippet(tmp_path, """
            import time

            def poll(fetch, alts, probe):
                while True:
                    try:
                        fetch()
                    except OSError:
                        for alt in alts:
                            probe(alt)
                            break
                        def cb():
                            return None
                        try:
                            raise ValueError("inner")
                        except ValueError:
                            pass
                        time.sleep(1.0)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_negative_bounded_inner_retry_in_daemon_loop(self, tmp_path):
        # the handler belongs to the bounded inner for, not the daemon
        # while-True — the retry IS bounded by construction
        fs = _scan_snippet(tmp_path, """
            import time

            def daemon(poll):
                while True:
                    for attempt in range(3):
                        try:
                            poll()
                            break
                        except OSError:
                            time.sleep(1.0)
        """)
        assert fs == []

    def test_negative_sleep_without_retry_shape(self, tmp_path):
        # a poll loop that never swallows exceptions is pacing, not retry
        fs = _scan_snippet(tmp_path, """
            import time

            def heartbeat(send):
                while True:
                    send()
                    time.sleep(30.0)
        """)
        assert fs == []

    def test_repo_retry_helper_is_clean(self):
        """The sanctioned helper itself (bounded for-loop) must not trip
        its own rule."""
        from deeplearning4j_tpu.analysis.rules.retry_loop import (
            UnboundedRetryRule)
        fs = scan_paths([str(PKG / "resilience" / "retry.py")],
                        [UnboundedRetryRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: non-atomic-state-write
# ---------------------------------------------------------------------
class TestNonAtomicStateWriteRule:
    def test_positive_json_dump_onto_final_path(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import json

            def save(path, state):
                with open(path, "w") as f:
                    json.dump(state, f)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_pickle_dump_wb(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import pickle

            def save(path, model):
                with open(path, "wb") as fh:
                    pickle.dump(model, fh)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_write_json_dumps(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import json

            def save(path, header, rows):
                with open(path, "w") as f:
                    f.write(json.dumps(header) + "\\n")
                    for r in rows:
                        f.write(r + "\\n")
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_zipfile_model_save(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import zipfile

            def save(path, blob):
                with zipfile.ZipFile(path, "w") as zf:
                    zf.writestr("model.bin", blob)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_negative_tmp_rename_idiom(self, tmp_path):
        # the sanctioned shape: dump to a tmp path, os.replace into place
        fs = _scan_snippet(tmp_path, """
            import json
            import os

            def save(path, state):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        """)
        assert fs == []

    def test_negative_append_sink_and_reads(self, tmp_path):
        # append-mode sinks are logs (JSONL exporters), not replace-
        # writes; reads and report-text writes are out of scope
        fs = _scan_snippet(tmp_path, """
            import json

            def log(path, rec):
                with open(path, "a") as f:
                    f.write(json.dumps(rec) + "\\n")

            def load(path):
                with open(path) as f:
                    return json.load(f)

            def report(path, html):
                with open(path, "w") as f:
                    f.write(html)
        """)
        assert fs == []

    def test_repo_atomic_helper_is_exempt(self):
        from deeplearning4j_tpu.analysis.rules.state_write import (
            NonAtomicStateWriteRule)
        fs = scan_paths([str(PKG / "resilience" / "durable.py")],
                        [NonAtomicStateWriteRule()], root=str(REPO))
        assert fs == []

    def test_repo_state_writers_are_clean(self):
        """The satellite fix set: every state writer the rule flagged
        when it landed now goes through the tmp-rename idiom."""
        from deeplearning4j_tpu.analysis.rules.state_write import (
            NonAtomicStateWriteRule)
        targets = ["util/checkpoint.py", "util/model_serializer.py",
                   "nlp/serializer.py", "nlp/pos_tagger.py",
                   "graph/deepwalk.py", "modelimport/dl4j.py",
                   "analysis/baseline.py", "eval/serde.py",
                   "eval/tools.py", "ui/storage.py"]
        fs = scan_paths([str(PKG / t) for t in targets],
                        [NonAtomicStateWriteRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: stale-world-snapshot
# ---------------------------------------------------------------------
class TestWorldSnapshotRule:
    def test_positive_module_scope_snapshot(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            WORLD = jax.process_count()
            MY_RANK = jax.process_index()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"] * 2

    def test_positive_class_scope_and_aliased(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            from jax import device_count

            class Trainer:
                n_devices = device_count()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_argument_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def shard(batch, world=jax.process_count()):
                return batch // world
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_lambda_default_is_definition_time(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            pick = lambda xs, w=jax.process_count(): xs[:w]
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_distributed_wrapper_snapshot(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            from deeplearning4j_tpu.parallel import distributed as dist

            RANK = dist.process_index()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_negative_call_time_reads(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def shard(batch):
                return batch // jax.process_count()

            class Trainer:
                def world(self):
                    return jax.process_count()

            pick = lambda xs: xs[jax.process_index()]
        """)
        assert fs == []

    def test_negative_nested_def_default_is_call_time(self, tmp_path):
        # the inner def's defaults evaluate when the OUTER runs — a
        # per-call event, not an import-time snapshot
        fs = _scan_snippet(tmp_path, """
            import jax

            def make_sharder():
                def shard(b, world=jax.process_count()):
                    return b // world
                return shard
        """)
        assert fs == []

    def test_negative_unrelated_module_scope_calls(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import os

            N = os.cpu_count()

            def device_count():
                return 1

            M = device_count()
        """)
        assert fs == []

    def test_repo_world_reads_are_call_time(self):
        """Repo self-scan for this rule specifically: every world read
        in the runtime-facing modules happens at call time (the elastic
        re-mesh contract)."""
        from deeplearning4j_tpu.analysis.rules.world_snapshot import (
            WorldSnapshotRule)
        fs = scan_paths([str(PKG)], [WorldSnapshotRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: replica-local-state-in-router (ISSUE 14)
# ---------------------------------------------------------------------
class TestReplicaStateRule:
    def _scan_fleet(self, tmp_path, source,
                    name="serving/fleet/router.py"):
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(source))
        return scan_file(str(p), ALL_RULES, root=str(tmp_path))

    def test_positive_engine_internals_in_router(self, tmp_path):
        fs = self._scan_fleet(tmp_path, """
            def score(rep):
                load = len(rep.engine._slots)
                depth = rep.engine._pending.depth()
                return load + depth
        """)
        assert _rules_of(fs) == ["replica-local-state-in-router"] * 2

    def test_positive_seating_and_pool_probes(self, tmp_path):
        fs = self._scan_fleet(tmp_path, """
            def dead_requests(engine):
                out = []
                if engine._seating is not None:
                    out.append(engine._seating)
                return out, engine.page_pool._free
        """, name="serving/fleet/migration.py")
        assert _rules_of(fs) == ["replica-local-state-in-router"] * 3

    def test_negative_public_accessors(self, tmp_path):
        fs = self._scan_fleet(tmp_path, """
            def score(rep, cfg):
                h = rep.engine.health()
                snap = rep.engine.queue_snapshot()
                load = (snap.depth + h["active_slots"]) / h["slots"]
                return load if rep.engine.is_ready() else 1e9

            def migrate(src, dst):
                entries = src.engine.detach_ledger()
                return dst.engine.admit_from_ledger(entries)
        """)
        assert fs == []

    def test_negative_own_private_state_via_self(self, tmp_path):
        fs = self._scan_fleet(tmp_path, """
            class Router:
                def __init__(self):
                    self._replicas = {}
                    self._affinity = {}

                def drop(self, rid):
                    self._replicas.pop(rid, None)
        """)
        assert fs == []

    def test_negative_outside_fleet_modules(self, tmp_path):
        """The engine's OWN modules (and everything else) may touch
        their internals — the rule scopes to serving/fleet/ only."""
        fs = self._scan_fleet(tmp_path, """
            def rebuild(engine):
                return [r for r in engine._slots if r is not None]
        """, name="serving/engine_helper.py")
        assert "replica-local-state-in-router" not in _rules_of(fs)

    def test_inline_suppression(self, tmp_path):
        fs = self._scan_fleet(tmp_path, """
            def peek(engine):
                # test-only chaos seam, justified
                return engine._slots  # tpulint: disable=replica-local-state-in-router
        """)
        assert _rules_of(fs) == []

    def test_repo_fleet_layer_is_clean(self):
        """The shipped fleet layer holds to its own contract: no
        foreign private reads — placement, migration, and autoscaling
        go through public engine accessors only."""
        from deeplearning4j_tpu.analysis.rules.replica_state import (
            ReplicaLocalStateInRouterRule)
        fs = scan_paths([str(PKG / "serving" / "fleet")],
                        [ReplicaLocalStateInRouterRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: wall-clock-in-traced-body (ISSUE 15)
# ---------------------------------------------------------------------
class TestWallClockRule:
    def test_positive_clock_in_jit_staged_body(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()          # frozen at trace time
                return x + t0
        """)
        assert "wall-clock-in-traced-body" in _rules_of(fs)

    def test_positive_clock_in_wrapped_function(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            def raw(x):
                return x * time.perf_counter()

            fast = jax.jit(raw)
        """)
        assert "wall-clock-in-traced-body" in _rules_of(fs)

    def test_positive_clock_in_step_builder_body(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            def _get_train_step(self):
                started = time.monotonic()   # per-build constant

                @jax.jit
                def step(p, batch):
                    return p, started
                return step
        """)
        # one in the builder body; the staged closure reads a captured
        # name, not the clock, so exactly one finding
        assert _rules_of(fs).count("wall-clock-in-traced-body") == 1

    def test_positive_aliased_import(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            from time import perf_counter as clock
            import jax

            def resolve_plan(net):
                jax.jit(lambda x: x)
                return clock()
        """)
        assert "wall-clock-in-traced-body" in _rules_of(fs)

    def test_negative_measure_around_the_dispatch(self, tmp_path):
        """The sanctioned idiom: clock reads AROUND a jitted call, in
        plain host code — never flagged."""
        fs = _scan_snippet(tmp_path, """
            import time

            def _run_dispatch(self, fn):
                t0 = time.perf_counter()
                out = fn()
                self._hist.observe(time.perf_counter() - t0)
                return out

            def step(self):
                now = time.monotonic()
                self._reap(now)
        """)
        assert "wall-clock-in-traced-body" not in _rules_of(fs)

    def test_negative_nested_runtime_thunk_is_host_code(self, tmp_path):
        """A nested def that is neither staged nor jit-building (a
        retry thunk) runs at call time — the innermost scope decides."""
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            def _get_retry_step(self):
                step = jax.jit(self._raw)

                def once():
                    t0 = time.monotonic()
                    out = step(t0)
                    return out, time.monotonic() - t0
                return once
        """)
        assert "wall-clock-in-traced-body" not in _rules_of(fs)

    def test_negative_module_scope_read(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            _T0 = time.time()   # import-time host constant, explicit

            @jax.jit
            def step(x):
                return x
        """)
        assert "wall-clock-in-traced-body" not in _rules_of(fs)

    def test_inline_suppression(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time
            import jax

            @jax.jit
            def step(x):
                # build stamp, deliberately frozen
                t0 = time.time()  # tpulint: disable=wall-clock-in-traced-body
                return x + t0
        """)
        assert "wall-clock-in-traced-body" not in _rules_of(fs)

    def test_repo_self_scan_clean(self):
        """The instrumented serving/resilience/monitoring hot paths
        read clocks only in host code — the shipped tree carries zero
        findings (and zero baseline entries) for this rule."""
        from deeplearning4j_tpu.analysis.rules.wall_clock import (
            WallClockInTracedBodyRule)
        fs = scan_paths([str(PKG)], [WallClockInTracedBodyRule()],
                        root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------
class TestSuppression:
    SRC = """
        import jax

        def _fit_batch(self, ds):
            loss = self.step(ds)
            self.score = float(loss)  # tpulint: disable=host-sync-in-hot-loop
            # justified: final-batch barrier
            # tpulint: disable=host-sync-in-hot-loop
            jax.block_until_ready(self.params)
    """

    def test_inline_and_next_line_suppressions(self, tmp_path):
        assert _scan_snippet(tmp_path, self.SRC) == []

    def test_unsuppressed_sibling_still_fires(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.SRC + """
            def _fit_other(self, ds):
                return float(self.step(ds))
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"]

    def test_disable_all_wildcard(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def _fit_batch(self, ds):
                return float(self.step(ds))  # tpulint: disable=all
        """)
        assert fs == []


# ---------------------------------------------------------------------
# baseline round-trip + CLI
# ---------------------------------------------------------------------
BAD_SRC = """
import jax

def _fit_batch(self, ds):
    return float(self.step(ds))
"""


class TestBaselineAndCli:
    def test_baseline_roundtrip(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        findings = scan_paths([str(mod)], root=str(tmp_path))
        assert _rules_of(findings) == ["host-sync-in-hot-loop"]

        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath), findings)
        again = scan_paths([str(mod)], root=str(tmp_path))
        new, matched, stale = bl.split_new(again, bl.load_baseline(str(bpath)))
        assert new == [] and matched == 1 and stale == []

        # a NEW violation is not absorbed by the old baseline
        mod.write_text(BAD_SRC + "\n\ndef _fit_more(self, ds):\n"
                       "    return float(self.step(ds))\n")
        third = scan_paths([str(mod)], root=str(tmp_path))
        new, matched, stale = bl.split_new(third, bl.load_baseline(str(bpath)))
        assert matched == 1 and len(new) == 1

    def test_baseline_stale_entries_reported(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        findings = scan_paths([str(mod)], root=str(tmp_path))
        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath), findings)
        mod.write_text("import jax\n")  # debt paid off
        new, matched, stale = bl.split_new(
            scan_paths([str(mod)], root=str(tmp_path)),
            bl.load_baseline(str(bpath)))
        assert new == [] and matched == 0 and len(stale) == 1

    def test_cli_json_exit_codes(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        rc = main([str(mod), "--format", "json",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["total"] == 1 and len(report["new"]) == 1
        assert report["new"][0]["rule"] == "host-sync-in-hot-loop"

        rc = main([str(mod), "--write-baseline",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        capsys.readouterr()
        assert rc == 0
        rc = main([str(mod), "--format", "json",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0 and report["new"] == [] and report["baselined"] == 1

    def test_cli_rule_selection_and_errors(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text("try:\n    pass\nexcept:\n    pass\n")
        rc = main([str(mod), "--no-baseline", "--rules", "bare-except"])
        capsys.readouterr()
        assert rc == 1
        rc = main([str(mod), "--no-baseline", "--rules", "mutable-default-arg"])
        capsys.readouterr()
        assert rc == 0
        assert main([str(mod), "--rules", "no-such-rule"]) == 2
        assert main([str(tmp_path / "missing.py")]) == 2
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule.id in out

    def test_parse_error_is_a_new_finding(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text("def broken(:\n")
        rc = main([str(mod), "--format", "json", "--no-baseline"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1 and report["new"][0]["rule"] == "parse-error"

    def test_single_rule_flag_and_baseline_scope(self, tmp_path, capsys):
        """--rule runs one rule; baseline entries of unselected rules
        are out of scope, not stale."""
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath),
                          scan_paths([str(mod)], root=str(tmp_path)))
        rc = main([str(mod), "--rule", "bare-except",
                   "--format", "json", "--baseline", str(bpath)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["stale_baseline"] == [] and report["total"] == 0
        rc = main([str(mod), "--rule", "host-sync-in-hot-loop",
                   "--format", "json", "--baseline", str(bpath)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0 and report["baselined"] == 1

    def test_stale_baseline_is_a_hard_failure(self, tmp_path, capsys):
        """ISSUE 13 ratchet hardening: paid-off debt must be ratcheted
        out of the baseline, or the lane fails."""
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath),
                          scan_paths([str(mod)], root=str(tmp_path)))
        mod.write_text("import jax\n")  # debt paid off
        rc = main([str(mod), "--format", "json",
                   "--baseline", str(bpath)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["new"] == [] and len(report["stale_baseline"]) == 1

    def test_update_baseline_refuses_error_severity(self, tmp_path,
                                                    capsys):
        """--update-baseline will not silently grandfather an
        error-severity finding; --allow-grandfather is the reviewed
        escape hatch, and warning-severity additions pass freely."""
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)   # host-sync: severity error
        bpath = tmp_path / bl.BASELINE_NAME
        rc = main([str(mod), "--update-baseline",
                   "--baseline", str(bpath)])
        capsys.readouterr()
        assert rc == 1 and not bpath.exists()
        rc = main([str(mod), "--update-baseline", "--allow-grandfather",
                   "--baseline", str(bpath)])
        capsys.readouterr()
        assert rc == 0 and bpath.exists()
        # ratchet down once the debt is paid: stale entry drops
        mod.write_text(BAD_SRC)
        rc = main([str(mod), "--update-baseline",
                   "--baseline", str(bpath)])
        capsys.readouterr()
        assert rc == 0  # unchanged content: nothing newly grandfathered
        # a WARNING-severity addition needs no flag
        mod.write_text(
            "import jax\nimport jax.numpy as jnp\n\n\n"
            "class Net:\n    def _fit_batch(self, ds):\n"
            "        return self.step(jnp.asarray(ds.features))\n")
        rc = main([str(mod), "--update-baseline",
                   "--baseline", str(bpath)])
        out = capsys.readouterr()
        assert rc == 0, out.err
        data = json.loads(bpath.read_text())
        assert all(e["rule"] == "device-transfer-in-hot-loop"
                   for e in data["findings"].values())


# ---------------------------------------------------------------------
# --diff: the O(diff) CI gate (ISSUE 13) against a synthetic repo
# ---------------------------------------------------------------------
import shutil
import subprocess


@pytest.mark.skipif(shutil.which("git") is None, reason="git required")
class TestDiffMode:
    CLEAN = "import jax\n\n\ndef prep(x):\n    return x\n"

    def _git(self, repo, *args):
        subprocess.run(
            ["git", "-C", str(repo), "-c", "user.email=t@t",
             "-c", "user.name=t", *args],
            check=True, capture_output=True)

    def _repo(self, tmp_path):
        """Three clean modules, committed; b.py then gains a violation
        in the working tree (the diff includes uncommitted changes)."""
        repo = tmp_path / "r"
        repo.mkdir()
        for name in ("a.py", "b.py", "c.py"):
            (repo / name).write_text(self.CLEAN)
        self._git(repo, "init", "-q")
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-qm", "seed")
        (repo / "b.py").write_text(
            self.CLEAN + "\n\ndef _fit_batch(self, ds):\n"
            "    return float(self.step(ds))\n")
        return repo

    def test_diff_scans_only_changed_modules(self, tmp_path, capsys):
        repo = self._repo(tmp_path)
        rc = main([str(repo), "--format", "json", "--diff", "HEAD",
                   "--baseline", str(repo / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["scanned_modules"] == 1
        assert report["total_modules"] == 3
        assert report["diff_base"] == "HEAD"
        assert [f["rule"] for f in report["new"]] == \
            ["host-sync-in-hot-loop"]
        assert report["new"][0]["path"] == "b.py"
        assert report["new"][0]["on_changed_line"] is True

    def test_diff_respects_baseline_without_stale_noise(self, tmp_path,
                                                        capsys):
        """A grandfathered finding in an UNCHANGED module is out of the
        diff's scope (not stale); one in the CHANGED module still
        absorbs its finding."""
        repo = self._repo(tmp_path)
        # plant a violation in c.py too and baseline the full scan
        (repo / "c.py").write_text(
            self.CLEAN + "\n\ndef _fit_other(self, ds):\n"
            "    return float(self.step(ds))\n")
        findings = scan_paths([str(repo)], root=str(repo))
        bpath = repo / bl.BASELINE_NAME
        bl.write_baseline(str(bpath), findings)
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-qm", "grandfathered")
        # new working-tree violation in b.py only
        (repo / "b.py").write_text(
            (repo / "b.py").read_text() +
            "\n\ndef _fit_more(self, ds):\n"
            "    return self.params.block_until_ready()\n")
        rc = main([str(repo), "--format", "json", "--diff", "HEAD",
                   "--baseline", str(bpath)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["scanned_modules"] == 1   # b.py only: O(diff)
        assert report["stale_baseline"] == []   # c.py is out of scope
        assert report["baselined"] == 1         # b.py's old finding
        assert [f["rule"] for f in report["new"]] == \
            ["host-sync-in-hot-loop"]
        # the full scan reproduces the identical grandfathered set:
        # fingerprint-for-fingerprint, plus the same single new finding
        rc = main([str(repo), "--format", "json",
                   "--baseline", str(bpath)])
        full = json.loads(capsys.readouterr().out)
        assert full["scanned_modules"] == 3
        assert full["baselined"] == 2 and full["stale_baseline"] == []
        assert [f["fingerprint"] for f in full["new"]] == \
            [f["fingerprint"] for f in report["new"]]

    def test_diff_refuses_baseline_writes_and_bad_ref(self, tmp_path,
                                                      capsys):
        repo = self._repo(tmp_path)
        bpath = repo / bl.BASELINE_NAME
        assert main([str(repo), "--diff", "HEAD", "--write-baseline",
                     "--baseline", str(bpath)]) == 2
        assert main([str(repo), "--diff", "HEAD", "--update-baseline",
                     "--baseline", str(bpath)]) == 2
        assert main([str(repo), "--diff", "no-such-ref",
                     "--baseline", str(bpath)]) == 2
        capsys.readouterr()

    def test_rule_subset_refuses_baseline_writes(self, tmp_path, capsys):
        """A rule-subset scan must never become the baseline either —
        it would wipe every other rule's grandfathered entries."""
        repo = self._repo(tmp_path)
        bpath = repo / bl.BASELINE_NAME
        assert main([str(repo), "--rule", "bare-except",
                     "--write-baseline", "--baseline", str(bpath)]) == 2
        assert main([str(repo), "--rules", "bare-except",
                     "--update-baseline", "--baseline", str(bpath)]) == 2
        assert not bpath.exists()
        capsys.readouterr()

    def test_diff_with_baseline_below_repo_toplevel(self, tmp_path,
                                                    capsys):
        """git emits toplevel-relative paths; a baseline anchored in a
        subdirectory must not make the diff scan silently empty."""
        repo = self._repo(tmp_path)
        sub = repo / "ci"
        sub.mkdir()
        rc = main([str(repo), "--format", "json", "--diff", "HEAD",
                   "--baseline", str(sub / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["scanned_modules"] == 1
        assert [f["rule"] for f in report["new"]] == \
            ["host-sync-in-hot-loop"]
        assert report["new"][0]["on_changed_line"] is True

    def test_changed_callee_flags_unchanged_caller(self, tmp_path,
                                                   capsys):
        """The impact closure: a changed CALLEE growing an effect
        surfaces its interprocedural finding in an UNCHANGED caller —
        the diff scan must include the reverse-import closure."""
        repo = tmp_path / "r2"
        repo.mkdir()
        (repo / "helper.py").write_text(
            "import jax\n\n\ndef summarize(x):\n    return x\n")
        (repo / "train.py").write_text(
            "import jax\nfrom helper import summarize\n\n\n"
            "def fit(model, batches):\n    for b in batches:\n"
            "        summarize(model.step(b))\n")
        (repo / "leaf.py").write_text(self.CLEAN)
        self._git(repo, "init", "-q")
        self._git(repo, "add", "-A")
        self._git(repo, "commit", "-qm", "seed")
        # the helper grows a sync; train.py is untouched
        (repo / "helper.py").write_text(
            "import jax\n\n\ndef summarize(x):\n"
            "    return jax.device_get(x)\n")
        rc = main([str(repo), "--format", "json", "--diff", "HEAD",
                   "--baseline", str(repo / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["scanned_modules"] == 2   # helper + its importer
        assert [f["path"] for f in report["new"]] == ["train.py"]
        assert report["new"][0]["rule"] == "host-sync-in-hot-loop"
        assert report["new"][0]["chain"]

    def test_untracked_new_module_is_scanned(self, tmp_path, capsys):
        """A brand-new module is invisible to `git diff <base>` until
        added — the gate must still scan it (fully changed)."""
        repo = self._repo(tmp_path)
        (repo / "b.py").write_text(self.CLEAN)  # undo the tracked change
        (repo / "fresh.py").write_text(
            "import jax\n\n\ndef _fit_batch(self, ds):\n"
            "    return float(self.step(ds))\n")
        rc = main([str(repo), "--format", "json", "--diff", "HEAD",
                   "--baseline", str(repo / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["scanned_modules"] == 1
        assert report["new"][0]["path"] == "fresh.py"
        assert report["new"][0]["on_changed_line"] is True


# ---------------------------------------------------------------------
# the gate: repo must scan clean against the committed baseline
# ---------------------------------------------------------------------
class TestSelfScan:
    def test_repo_has_zero_non_baselined_findings(self, capsys):
        rc = main([str(PKG), "--format", "json",
                   "--baseline", str(REPO / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert report["new"] == [], (
            "new tpulint findings (fix them, suppress with justification, "
            "or — for pre-existing debt only — re-baseline):\n" +
            "\n".join(f"{f['path']}:{f['line']} [{f['rule']}] {f['message']}"
                      for f in report["new"]))
        assert rc == 0

    def test_committed_baseline_has_no_stale_entries(self, capsys):
        rc = main([str(PKG), "--format", "json",
                   "--baseline", str(REPO / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["stale_baseline"] == [], (
            "baseline entries no longer observed — ratchet down with "
            "--write-baseline")

    def test_every_rule_family_is_registered(self):
        assert {r.id for r in ALL_RULES} == {
            "host-sync-in-hot-loop", "device-transfer-in-hot-loop",
            "tracer-leak", "recompile-hazard",
            "dtype-promotion", "int8-promotion-in-dispatch",
            "unlocked-thread-state", "bare-except",
            "mutable-default-arg", "unbounded-retry",
            "non-atomic-state-write", "stale-world-snapshot",
            "lock-held-across-dispatch",
            "donation-use-after-consume", "jit-key-drift",
            "replica-local-state-in-router",
            "wall-clock-in-traced-body"}
        assert RULES_BY_ID["host-sync-in-hot-loop"].severity == "error"
        assert RULES_BY_ID["device-transfer-in-hot-loop"].severity == \
            "warning"
        assert RULES_BY_ID["donation-use-after-consume"].severity == \
            "error"
        assert RULES_BY_ID["jit-key-drift"].severity == "warning"
