"""tpulint (deeplearning4j_tpu/analysis): per-rule positive/negative
fixtures, inline suppressions, baseline round-trip, CLI contract, and the
self-scan gate that keeps the repo clean beyond the committed baseline."""

import json
import os
import textwrap
from pathlib import Path

import pytest

from deeplearning4j_tpu.analysis import baseline as bl
from deeplearning4j_tpu.analysis.cli import main
from deeplearning4j_tpu.analysis.core import scan_file, scan_paths
from deeplearning4j_tpu.analysis.rules import ALL_RULES, RULES_BY_ID

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "deeplearning4j_tpu"


def _scan_snippet(tmp_path, source, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return scan_file(str(p), ALL_RULES, root=str(tmp_path))


def _rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------
# rule: host-sync-in-hot-loop
# ---------------------------------------------------------------------
class TestHostSyncRule:
    def test_positive_float_and_block_in_per_batch_path(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class Net:
                def _fit_batch(self, ds):
                    loss = self.step(ds)
                    self.score = float(loss)
                    jax.block_until_ready(self.params)
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"] * 2

    def test_positive_item_and_device_get_in_fit_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def fit(model, batches):
                for b in batches:
                    loss = model.step(b)
                    print(loss.item())
                    jax.device_get(loss)
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"] * 2

    def test_negative_outside_hot_path_or_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def fit(model, b):
                loss = model.step(b)      # no loop at this level
                return float(loss)

            def score(model, b):
                return float(model.loss(b))
        """)
        assert fs == []

    def test_negative_module_without_jax_is_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import numpy as np

            def fit(stats, batches):
                for b in batches:
                    stats.append(float(np.mean(b)))
        """)
        assert fs == []

    def test_negative_benign_scalar_casts_and_host_literals(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import numpy as np

            def _fit_batch(self, ds, seqs):
                n = int(ds.features.shape[0])
                m = float(len(seqs))
                lens = np.asarray([len(s) for s in seqs])
                return n, m, lens
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: device-transfer-in-hot-loop
# ---------------------------------------------------------------------
class TestDeviceTransferRule:
    def test_positive_asarray_and_device_put_in_per_batch_path(self,
                                                               tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Net:
                def _fit_batch(self, ds):
                    x = jnp.asarray(ds.features)
                    y = jax.device_put(ds.labels)
                    return self.step(x, y)
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"] * 2

    def test_positive_jnp_array_in_fit_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            def fit(model, batches):
                for b in batches:
                    model.step(jnp.array(b.features))
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"]

    def test_negative_outside_hot_path_and_constants(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            def prepare(ds):
                # not a fit/epoch hot path: staging here is fine
                return jnp.asarray(ds.features)

            class Net:
                def _fit_batch(self, ds):
                    pad = jnp.asarray(3)  # literal scalar, not a batch
                    return self.step(ds, pad)

            def fit(model, x):
                x = jax.device_put(x)  # once, before the loop
                for _ in range(3):
                    model.step(x)
        """)
        assert fs == []

    def test_negative_module_without_jax_is_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def _fit_batch(self, ds):
                return jnp.asarray(ds.features)
        """)
        assert fs == []

    def test_suppression_and_baseline_cover_jit_boundary_remnants(
            self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            class Net:
                def _fit_batch(self, ds):
                    # compat path when prefetch is off
                    # tpulint: disable=device-transfer-in-hot-loop
                    x = jnp.asarray(ds.features)
                    return self.step(x)
        """)
        assert fs == []

    def test_positive_per_step_table_rebuild(self, tmp_path):
        """The serving decode-loop shape this rule grew to catch: the
        host rebuilds and re-uploads the full page table every step
        even when nothing changed."""
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Engine:
                def _dispatch_step(self):
                    table = jnp.asarray(self._tables_np())
                    return self._decode(self.pool[table])

                def step(self):
                    t = jax.device_put(self._tables_np())
                    return self._decode(t)
        """)
        assert _rules_of(fs) == ["device-transfer-in-hot-loop"] * 2
        assert any("per-step path" in f.message for f in fs)

    def test_negative_cached_table_path(self, tmp_path):
        """The engine's cached-table fix shape: the transfer lives in a
        cache helper OUTSIDE the per-step names, rebuilt only after an
        invalidating mutation — steady-state steps re-upload nothing."""
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp

            class Engine:
                def _tables_dev(self):
                    if self._cache is None:
                        self._cache = jnp.asarray(self._tables_np())
                    return self._cache

                def _invalidate_tables(self):
                    self._cache = None

                def _dispatch_step(self):
                    return self._decode(self.pool[self._tables_dev()])
        """)
        assert fs == []

    def test_negative_nested_step_is_jit_body(self, tmp_path):
        """A nested ``def step(...)`` is a jitted/scan body — its
        jnp.asarray is a trace-time constant, not a per-step H2D."""
        fs = _scan_snippet(tmp_path, """
            import jax
            import jax.numpy as jnp

            class Net:
                def _get_train_step(self):
                    def step(params, batch):
                        decay = jnp.asarray(self.decay_schedule)
                        return params, decay
                    return jax.jit(step)
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: tracer-leak
# ---------------------------------------------------------------------
class TestTracerLeakRule:
    def test_positive_self_assign_in_decorated_jit(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class M:
                @jax.jit
                def step(self, x):
                    self.cache = x * 2
                    return x
        """)
        assert _rules_of(fs) == ["tracer-leak"]

    def test_positive_global_assign_in_wrapped_fn(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            _LAST = None

            def step(x):
                global _LAST
                _LAST = x * 2
                return x

            fast_step = jax.jit(step)
        """)
        assert _rules_of(fs) == ["tracer-leak"]

    def test_negative_unjitted_function_may_mutate(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            class M:
                def record(self, x):
                    self.cache = x * 2
                    return x
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: recompile-hazard
# ---------------------------------------------------------------------
class TestRecompileHazardRule:
    def test_positive_jit_in_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def run(fns, x):
                for f in fns:
                    y = jax.jit(f)(x)
                return y
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_positive_list_static_argnums(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def f(x, n):
                return x * n

            g = jax.jit(f, static_argnums=[1])
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_positive_branch_on_traced_arg(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_negative_static_arg_branch_and_none_check(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("train",))
            def f(x, mask, train):
                if train:                 # static: fine
                    x = x * 2
                if mask is None:          # identity check: fine
                    return x
                if x.shape[0] > 4:        # shape metadata: fine
                    return x + 1
                return x
        """)
        assert fs == []

    def test_positive_env_read_in_jit_building_step_builder(
            self, tmp_path):
        """ISSUE 11: os.environ resolved inside a step-builder body —
        the value bakes into the trace but sits in no jit key, so a
        flip keeps the stale compiled step (the BENCH_FUSE class)."""
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            class Net:
                def _get_train_step(self, carry):
                    fused = os.environ.get("MY_FUSE") == "1"

                    def step(p, x):
                        return p * x if fused else p + x

                    return jax.jit(step)
        """)
        assert _rules_of(fs) == ["recompile-hazard"]
        assert "os.environ read inside step-builder" in fs[0].message

    def test_positive_env_read_in_plan_resolution_name(self, tmp_path):
        """Name-matched plan-resolution seams are flagged even when the
        jit construction lives in a helper they call."""
        fs = _scan_snippet(tmp_path, """
            import os

            def resolve_plan(net):
                return os.getenv("MY_PLAN", "xla")
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_positive_env_subscript_in_step_builder(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            def _get_output_fn(net):
                impl = os.environ["MY_IMPL"]
                return jax.jit(lambda x: x)
        """)
        assert _rules_of(fs) == ["recompile-hazard"]

    def test_negative_env_read_outside_builders(self, tmp_path):
        """Env reads at module scope or in ordinary config functions are
        someone else's business — only trace-building bodies retrace."""
        fs = _scan_snippet(tmp_path, """
            import os
            import jax

            DEFAULT_DIR = os.environ.get("MY_DATA_DIR", "/tmp")

            def load_config():
                return os.environ.get("MY_MODE", "prod")

            def get_step(cache, fn):
                return jax.jit(fn)
        """)
        assert fs == []

    def test_negative_cached_jit_outside_loop(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def get_step(cache, fn):
                if "step" not in cache:
                    cache["step"] = jax.jit(fn, static_argnums=(2,))
                return cache["step"]
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: dtype-promotion
# ---------------------------------------------------------------------
class TestDtypePromotionRule:
    def test_positive_np_float64_in_jax_module(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp
            import numpy as np

            def prep(x):
                return jnp.asarray(np.asarray(x, np.float64))
        """)
        assert _rules_of(fs) == ["dtype-promotion"]

    def test_positive_enable_x64_outside_shim(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            jax.config.update("jax_enable_x64", True)
        """)
        assert _rules_of(fs) == ["dtype-promotion"]

    def test_negative_no_jax_import(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import numpy as np

            def stats(x):
                return np.asarray(x, np.float64).mean()
        """)
        assert fs == []

    def test_negative_gradient_check_module_exempt(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax.numpy as jnp
            import numpy as np

            def check(p):
                return jnp.asarray(p, jnp.float64)
        """, name="gradient_check.py")
        assert fs == []


# ---------------------------------------------------------------------
# rule: unlocked-thread-state
# ---------------------------------------------------------------------
class TestThreadSharedStateRule:
    def test_positive_unlocked_self_mutation_in_target(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading

            class Server:
                def start(self):
                    self._t = threading.Thread(target=self._loop)
                    self._t.start()

                def _loop(self):
                    self.count = 0
                    while True:
                        self.count += 1
        """)
        assert _rules_of(fs) == ["unlocked-thread-state"] * 2

    def test_negative_mutation_under_lock(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading

            class Server:
                def start(self):
                    self._lock = threading.Lock()
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    while True:
                        with self._lock:
                            self.count = 1
        """)
        assert fs == []

    def test_negative_queue_handoff_untouched(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import queue
            import threading

            class Server:
                def start(self):
                    self.q = queue.Queue()
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    while True:
                        item = self.q.get()
                        item.event.set()
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rules: hygiene
# ---------------------------------------------------------------------
class TestHygieneRules:
    def test_positive_bare_except_and_mutable_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def load(path, cache={}):
                try:
                    return cache[path]
                except:
                    return None
        """)
        assert _rules_of(fs) == ["bare-except", "mutable-default-arg"]

    def test_negative_typed_except_and_none_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            def load(path, cache=None):
                try:
                    return (cache or {})[path]
                except KeyError:
                    return None
        """)
        assert fs == []


# ---------------------------------------------------------------------
# rule: lock-held-across-dispatch
# ---------------------------------------------------------------------
class TestLockHeldAcrossDispatchRule:
    def test_positive_jitted_and_syncs_under_lock(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax
            from functools import partial

            @jax.jit
            def _dispatch(x):
                return x + 1

            @partial(jax.jit, donate_argnums=(0,))
            def _donate(x):
                return x * 2

            class Engine:
                def step(self, x):
                    with self._lock:
                        y = _dispatch(x)
                        z = _donate(x)
                        w = self.net.rnn_time_step(x)
                        jax.device_get(y)
                        y.block_until_ready()
                    return y
        """)
        assert _rules_of(fs) == ["lock-held-across-dispatch"] * 5

    def test_positive_known_dispatch_helpers(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            from deeplearning4j_tpu.util.decoding import step_tokens
            from deeplearning4j_tpu.serving.paging import gather_pages

            class Engine:
                def step(self, toks):
                    with self._lock:
                        view = gather_pages(self.pools, self.table,
                                            length=8)
                        return step_tokens(self.net, toks, 12)
        """)
        assert _rules_of(fs) == ["lock-held-across-dispatch"] * 2

    def test_negative_snapshot_under_lock_dispatch_outside(self,
                                                           tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Engine:
                def step(self, x):
                    with self._lock:
                        snap = dict(self.state)   # host-only under lock
                    return _dispatch(snap)        # dispatch outside
        """)
        assert fs == []

    def test_negative_condition_wait_is_the_queue_idiom(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Q:
                def pop(self, x):
                    with self._cond:
                        self._cond.wait(0.1)
                        return _dispatch(x)       # cond, not a lock
        """)
        assert fs == []

    def test_negative_lock_in_outer_function_not_this_scope(self,
                                                            tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            def outer(self, x):
                with self._lock:
                    def cb():
                        return _dispatch(x)       # runs LATER, unlocked
                    self.cb = cb
        """)
        assert fs == []

    def test_negative_lambda_defined_under_lock_runs_later(self,
                                                           tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            def outer(self, x):
                with self._lock:
                    self.cb = lambda: _dispatch(x)  # deferred, unlocked
        """)
        assert fs == []

    def test_inline_suppression(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import threading
            import jax

            @jax.jit
            def _dispatch(x):
                return x + 1

            class Engine:
                def step(self, x):
                    with self._lock:
                        # single-threaded dispatcher: submit/health
                        # read lock-free, so only step() waits here
                        # tpulint: disable=lock-held-across-dispatch
                        return _dispatch(x)
        """)
        assert fs == []

    def test_repo_serving_parallel_hot_paths_are_clean(self):
        """The serving engine keeps submit/health/metrics OFF its step
        lock and its dispatches behind method seams that snapshot
        first; the repo carries no lexical lock-held dispatch (any
        future justified hold must carry an inline suppression)."""
        from deeplearning4j_tpu.analysis.rules.lock_dispatch import (
            LockHeldAcrossDispatchRule)
        fs = scan_paths([str(PKG / "serving"), str(PKG / "parallel"),
                         str(PKG / "nn"), str(PKG / "pipeline")],
                        [LockHeldAcrossDispatchRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: unbounded-retry
# ---------------------------------------------------------------------
class TestUnboundedRetryRule:
    def test_positive_while_true_sleep_swallowing_except(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def poll(fetch):
                while True:
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(1.0)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_positive_sleep_outside_handler_still_counts(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def wait_for(ready):
                while True:
                    try:
                        if ready():
                            return
                    except OSError:
                        pass
                    time.sleep(0.5)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_negative_bounded_attempts_via_raise(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def fetch_with_cap(fetch, limit=5):
                attempts = 0
                while True:
                    try:
                        return fetch()
                    except ConnectionError:
                        attempts += 1
                        if attempts >= limit:
                            raise
                        time.sleep(0.1 * attempts)
        """)
        assert fs == []

    def test_negative_for_range_and_condition_loops(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import time

            def bounded(fetch):
                for attempt in range(5):
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(0.1)

            def stoppable(fetch, stop):
                while not stop.is_set():
                    try:
                        return fetch()
                    except ConnectionError:
                        time.sleep(0.1)
        """)
        assert fs == []

    def test_positive_nested_escape_does_not_bound(self, tmp_path):
        # the break exits only the inner for, the return lives in a
        # nested def, and the raise is swallowed by an inner try: none
        # of them bounds the retry — still unbounded
        fs = _scan_snippet(tmp_path, """
            import time

            def poll(fetch, alts, probe):
                while True:
                    try:
                        fetch()
                    except OSError:
                        for alt in alts:
                            probe(alt)
                            break
                        def cb():
                            return None
                        try:
                            raise ValueError("inner")
                        except ValueError:
                            pass
                        time.sleep(1.0)
        """)
        assert _rules_of(fs) == ["unbounded-retry"]

    def test_negative_bounded_inner_retry_in_daemon_loop(self, tmp_path):
        # the handler belongs to the bounded inner for, not the daemon
        # while-True — the retry IS bounded by construction
        fs = _scan_snippet(tmp_path, """
            import time

            def daemon(poll):
                while True:
                    for attempt in range(3):
                        try:
                            poll()
                            break
                        except OSError:
                            time.sleep(1.0)
        """)
        assert fs == []

    def test_negative_sleep_without_retry_shape(self, tmp_path):
        # a poll loop that never swallows exceptions is pacing, not retry
        fs = _scan_snippet(tmp_path, """
            import time

            def heartbeat(send):
                while True:
                    send()
                    time.sleep(30.0)
        """)
        assert fs == []

    def test_repo_retry_helper_is_clean(self):
        """The sanctioned helper itself (bounded for-loop) must not trip
        its own rule."""
        from deeplearning4j_tpu.analysis.rules.retry_loop import (
            UnboundedRetryRule)
        fs = scan_paths([str(PKG / "resilience" / "retry.py")],
                        [UnboundedRetryRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: non-atomic-state-write
# ---------------------------------------------------------------------
class TestNonAtomicStateWriteRule:
    def test_positive_json_dump_onto_final_path(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import json

            def save(path, state):
                with open(path, "w") as f:
                    json.dump(state, f)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_pickle_dump_wb(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import pickle

            def save(path, model):
                with open(path, "wb") as fh:
                    pickle.dump(model, fh)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_write_json_dumps(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import json

            def save(path, header, rows):
                with open(path, "w") as f:
                    f.write(json.dumps(header) + "\\n")
                    for r in rows:
                        f.write(r + "\\n")
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_positive_zipfile_model_save(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import zipfile

            def save(path, blob):
                with zipfile.ZipFile(path, "w") as zf:
                    zf.writestr("model.bin", blob)
        """)
        assert _rules_of(fs) == ["non-atomic-state-write"]

    def test_negative_tmp_rename_idiom(self, tmp_path):
        # the sanctioned shape: dump to a tmp path, os.replace into place
        fs = _scan_snippet(tmp_path, """
            import json
            import os

            def save(path, state):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(state, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        """)
        assert fs == []

    def test_negative_append_sink_and_reads(self, tmp_path):
        # append-mode sinks are logs (JSONL exporters), not replace-
        # writes; reads and report-text writes are out of scope
        fs = _scan_snippet(tmp_path, """
            import json

            def log(path, rec):
                with open(path, "a") as f:
                    f.write(json.dumps(rec) + "\\n")

            def load(path):
                with open(path) as f:
                    return json.load(f)

            def report(path, html):
                with open(path, "w") as f:
                    f.write(html)
        """)
        assert fs == []

    def test_repo_atomic_helper_is_exempt(self):
        from deeplearning4j_tpu.analysis.rules.state_write import (
            NonAtomicStateWriteRule)
        fs = scan_paths([str(PKG / "resilience" / "durable.py")],
                        [NonAtomicStateWriteRule()], root=str(REPO))
        assert fs == []

    def test_repo_state_writers_are_clean(self):
        """The satellite fix set: every state writer the rule flagged
        when it landed now goes through the tmp-rename idiom."""
        from deeplearning4j_tpu.analysis.rules.state_write import (
            NonAtomicStateWriteRule)
        targets = ["util/checkpoint.py", "util/model_serializer.py",
                   "nlp/serializer.py", "nlp/pos_tagger.py",
                   "graph/deepwalk.py", "modelimport/dl4j.py",
                   "analysis/baseline.py", "eval/serde.py",
                   "eval/tools.py", "ui/storage.py"]
        fs = scan_paths([str(PKG / t) for t in targets],
                        [NonAtomicStateWriteRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# rule: stale-world-snapshot
# ---------------------------------------------------------------------
class TestWorldSnapshotRule:
    def test_positive_module_scope_snapshot(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            WORLD = jax.process_count()
            MY_RANK = jax.process_index()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"] * 2

    def test_positive_class_scope_and_aliased(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            from jax import device_count

            class Trainer:
                n_devices = device_count()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_argument_default(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def shard(batch, world=jax.process_count()):
                return batch // world
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_lambda_default_is_definition_time(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            pick = lambda xs, w=jax.process_count(): xs[:w]
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_positive_distributed_wrapper_snapshot(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            from deeplearning4j_tpu.parallel import distributed as dist

            RANK = dist.process_index()
        """)
        assert _rules_of(fs) == ["stale-world-snapshot"]

    def test_negative_call_time_reads(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def shard(batch):
                return batch // jax.process_count()

            class Trainer:
                def world(self):
                    return jax.process_count()

            pick = lambda xs: xs[jax.process_index()]
        """)
        assert fs == []

    def test_negative_nested_def_default_is_call_time(self, tmp_path):
        # the inner def's defaults evaluate when the OUTER runs — a
        # per-call event, not an import-time snapshot
        fs = _scan_snippet(tmp_path, """
            import jax

            def make_sharder():
                def shard(b, world=jax.process_count()):
                    return b // world
                return shard
        """)
        assert fs == []

    def test_negative_unrelated_module_scope_calls(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import os

            N = os.cpu_count()

            def device_count():
                return 1

            M = device_count()
        """)
        assert fs == []

    def test_repo_world_reads_are_call_time(self):
        """Repo self-scan for this rule specifically: every world read
        in the runtime-facing modules happens at call time (the elastic
        re-mesh contract)."""
        from deeplearning4j_tpu.analysis.rules.world_snapshot import (
            WorldSnapshotRule)
        fs = scan_paths([str(PKG)], [WorldSnapshotRule()], root=str(REPO))
        assert fs == []


# ---------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------
class TestSuppression:
    SRC = """
        import jax

        def _fit_batch(self, ds):
            loss = self.step(ds)
            self.score = float(loss)  # tpulint: disable=host-sync-in-hot-loop
            # justified: final-batch barrier
            # tpulint: disable=host-sync-in-hot-loop
            jax.block_until_ready(self.params)
    """

    def test_inline_and_next_line_suppressions(self, tmp_path):
        assert _scan_snippet(tmp_path, self.SRC) == []

    def test_unsuppressed_sibling_still_fires(self, tmp_path):
        fs = _scan_snippet(tmp_path, self.SRC + """
            def _fit_other(self, ds):
                return float(self.step(ds))
        """)
        assert _rules_of(fs) == ["host-sync-in-hot-loop"]

    def test_disable_all_wildcard(self, tmp_path):
        fs = _scan_snippet(tmp_path, """
            import jax

            def _fit_batch(self, ds):
                return float(self.step(ds))  # tpulint: disable=all
        """)
        assert fs == []


# ---------------------------------------------------------------------
# baseline round-trip + CLI
# ---------------------------------------------------------------------
BAD_SRC = """
import jax

def _fit_batch(self, ds):
    return float(self.step(ds))
"""


class TestBaselineAndCli:
    def test_baseline_roundtrip(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        findings = scan_paths([str(mod)], root=str(tmp_path))
        assert _rules_of(findings) == ["host-sync-in-hot-loop"]

        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath), findings)
        again = scan_paths([str(mod)], root=str(tmp_path))
        new, matched, stale = bl.split_new(again, bl.load_baseline(str(bpath)))
        assert new == [] and matched == 1 and stale == []

        # a NEW violation is not absorbed by the old baseline
        mod.write_text(BAD_SRC + "\n\ndef _fit_more(self, ds):\n"
                       "    return float(self.step(ds))\n")
        third = scan_paths([str(mod)], root=str(tmp_path))
        new, matched, stale = bl.split_new(third, bl.load_baseline(str(bpath)))
        assert matched == 1 and len(new) == 1

    def test_baseline_stale_entries_reported(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        findings = scan_paths([str(mod)], root=str(tmp_path))
        bpath = tmp_path / bl.BASELINE_NAME
        bl.write_baseline(str(bpath), findings)
        mod.write_text("import jax\n")  # debt paid off
        new, matched, stale = bl.split_new(
            scan_paths([str(mod)], root=str(tmp_path)),
            bl.load_baseline(str(bpath)))
        assert new == [] and matched == 0 and len(stale) == 1

    def test_cli_json_exit_codes(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text(BAD_SRC)
        rc = main([str(mod), "--format", "json",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["total"] == 1 and len(report["new"]) == 1
        assert report["new"][0]["rule"] == "host-sync-in-hot-loop"

        rc = main([str(mod), "--write-baseline",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        capsys.readouterr()
        assert rc == 0
        rc = main([str(mod), "--format", "json",
                   "--baseline", str(tmp_path / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0 and report["new"] == [] and report["baselined"] == 1

    def test_cli_rule_selection_and_errors(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text("try:\n    pass\nexcept:\n    pass\n")
        rc = main([str(mod), "--no-baseline", "--rules", "bare-except"])
        capsys.readouterr()
        assert rc == 1
        rc = main([str(mod), "--no-baseline", "--rules", "mutable-default-arg"])
        capsys.readouterr()
        assert rc == 0
        assert main([str(mod), "--rules", "no-such-rule"]) == 2
        assert main([str(tmp_path / "missing.py")]) == 2
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule.id in out

    def test_parse_error_is_a_new_finding(self, tmp_path, capsys):
        mod = tmp_path / "m.py"
        mod.write_text("def broken(:\n")
        rc = main([str(mod), "--format", "json", "--no-baseline"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1 and report["new"][0]["rule"] == "parse-error"


# ---------------------------------------------------------------------
# the gate: repo must scan clean against the committed baseline
# ---------------------------------------------------------------------
class TestSelfScan:
    def test_repo_has_zero_non_baselined_findings(self, capsys):
        rc = main([str(PKG), "--format", "json",
                   "--baseline", str(REPO / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert report["new"] == [], (
            "new tpulint findings (fix them, suppress with justification, "
            "or — for pre-existing debt only — re-baseline):\n" +
            "\n".join(f"{f['path']}:{f['line']} [{f['rule']}] {f['message']}"
                      for f in report["new"]))
        assert rc == 0

    def test_committed_baseline_has_no_stale_entries(self, capsys):
        rc = main([str(PKG), "--format", "json",
                   "--baseline", str(REPO / bl.BASELINE_NAME)])
        report = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert report["stale_baseline"] == [], (
            "baseline entries no longer observed — ratchet down with "
            "--write-baseline")

    def test_every_rule_family_is_registered(self):
        assert {r.id for r in ALL_RULES} == {
            "host-sync-in-hot-loop", "device-transfer-in-hot-loop",
            "tracer-leak", "recompile-hazard",
            "dtype-promotion", "unlocked-thread-state", "bare-except",
            "mutable-default-arg", "unbounded-retry",
            "non-atomic-state-write", "stale-world-snapshot",
            "lock-held-across-dispatch"}
        assert RULES_BY_ID["host-sync-in-hot-loop"].severity == "error"
        assert RULES_BY_ID["device-transfer-in-hot-loop"].severity == \
            "warning"
