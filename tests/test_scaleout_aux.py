"""Scaleout auxiliary tests: EarlyStoppingParallelTrainer, CLI main,
streaming pub/sub + serving route, object-store IO (SURVEY §2.5)."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.earlystopping.core import (
    EarlyStoppingConfiguration, MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.parallel.early_stopping import (
    EarlyStoppingParallelTrainer,
)
from deeplearning4j_tpu.storage import Downloader, Uploader
from deeplearning4j_tpu.streaming import ArrayHub, ArraySubscriber, ServeRoute


def small_net():
    conf = (NeuralNetConfiguration.Builder().seed(0)
            .updater(Adam(learning_rate=0.02)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def toy_iter(n=64, batch=16):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return ArrayDataSetIterator(x, y, batch_size=batch)


class TestEarlyStoppingParallel:
    def test_trains_and_terminates(self):
        net = small_net()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(6),
                ScoreImprovementEpochTerminationCondition(3)],
        )
        trainer = EarlyStoppingParallelTrainer(cfg, net, toy_iter(),
                                               prefetch_buffer=0)
        res = trainer.fit()
        assert res.total_epochs <= 7
        assert res.best_model is not None
        assert np.isfinite(res.best_model_score)
        assert res.score_vs_epoch  # recorded every epoch


class TestParallelWrapperMain:
    def test_cli_end_to_end(self, tmp_path):
        from deeplearning4j_tpu.parallel.main import main
        from deeplearning4j_tpu.util import model_serializer

        # save a model + CSV, then run the CLI
        net = small_net()
        model_in = str(tmp_path / "model.zip")
        model_out = str(tmp_path / "trained.zip")
        model_serializer.write_model(net, model_in)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((60, 4))
        y = (x.sum(1) > 0).astype(int)
        csv = str(tmp_path / "train.csv")
        np.savetxt(csv, np.column_stack([x, y]), delimiter=",", fmt="%.6g")

        rc = main(["--model", model_in, "--data", csv,
                   "--label-index", "4", "--num-classes", "2",
                   "--batch-size", "16", "--epochs", "3",
                   "--prefetch-buffer", "0", "--output", model_out])
        assert rc == 0
        assert os.path.exists(model_out)
        restored = model_serializer.restore_model(model_out)
        assert restored.iteration_count > 0

    def test_parser_validates(self):
        from deeplearning4j_tpu.parallel.main import build_parser
        with pytest.raises(SystemExit):
            build_parser().parse_args(["--model", "m.zip"])  # missing args


class TestStreaming:
    def test_pub_sub_roundtrip(self):
        hub = ArrayHub()
        try:
            sub = ArraySubscriber(hub.port, timeout=5)
            time.sleep(0.05)  # let the hub register the subscriber
            x = np.arange(12, dtype=np.float32).reshape(3, 4)
            assert hub.publish(features=x, step=np.int64(7)) == 1
            frame = sub.next()
            np.testing.assert_array_equal(frame["features"], x)
            assert int(frame["step"]) == 7
            sub.close()
        finally:
            hub.close()

    def test_serve_route(self):
        in_hub, out_hub = ArrayHub(), ArrayHub()
        route = None
        try:
            out_sub = ArraySubscriber(out_hub.port, timeout=5)
            time.sleep(0.05)
            route = ServeRoute(lambda f: f @ np.ones((4, 2), np.float32),
                               in_port=in_hub.port, out_hub=out_hub)
            time.sleep(0.05)
            x = np.ones((5, 4), np.float32)
            assert in_hub.publish(features=x) == 1
            frame = out_sub.next()
            np.testing.assert_allclose(frame["predictions"],
                                       np.full((5, 2), 4.0))
            out_sub.close()
        finally:
            if route:
                route.stop()
            in_hub.close()
            out_hub.close()


class TestObjectStore:
    def test_file_backend_roundtrip(self, tmp_path):
        src = tmp_path / "a.bin"
        src.write_bytes(b"hello")
        up, down = Uploader(), Downloader()
        url = f"file://{tmp_path}/store/a.bin"
        up.upload(str(src), url)
        out = str(tmp_path / "back.bin")
        down.download(url, out)
        assert open(out, "rb").read() == b"hello"
        assert any("a.bin" in u
                   for u in down.list(f"file://{tmp_path}/store"))

    def test_upload_directory(self, tmp_path):
        d = tmp_path / "data"
        (d / "sub").mkdir(parents=True)
        (d / "x.txt").write_text("1")
        (d / "sub" / "y.txt").write_text("2")
        n = Uploader().upload_directory(str(d), f"file://{tmp_path}/dst")
        assert n == 2
        assert (tmp_path / "dst" / "sub" / "y.txt").read_text() == "2"

    def test_s3_requires_boto(self):
        with pytest.raises((RuntimeError, Exception)):
            Uploader().upload("/tmp/x", "s3://bucket/key")

    def test_unknown_scheme(self):
        with pytest.raises(ValueError, match="unsupported"):
            Downloader().list("ftp://host/x")


class TestNewListeners:
    def test_param_and_gradient_listener(self, tmp_path):
        from deeplearning4j_tpu.optimize.listeners import (
            ParamAndGradientIterationListener,
        )
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updater import Sgd
        import numpy as np

        conf = (NeuralNetConfiguration.Builder()
                .seed(2).updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=4))
                .layer(OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = str(tmp_path / "stats.tsv")
        net.add_listener(ParamAndGradientIterationListener(output_file=out))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        net.fit(x, y, epochs=3, batch_size=16)
        lines = open(out).read().strip().splitlines()
        assert lines[0].startswith("iteration\tscore")
        assert len(lines) >= 4
        # update column becomes finite once history exists
        last = lines[-1].split("\t")
        assert float(last[2]) > 0 and np.isfinite(float(last[3]))

    def test_sleepy_listener(self):
        import time as _time
        from deeplearning4j_tpu.optimize.listeners import (
            SleepyTrainingListener,
        )
        sl = SleepyTrainingListener(sleep_iteration_ms=30)
        t0 = _time.perf_counter()
        sl.iteration_done(None, 1, 0.0)
        assert _time.perf_counter() - t0 >= 0.025
