"""int8 KV page pool (serving/quant.py + PagedKVConfig(kv_dtype="int8")):
quantization primitives and their exactness contracts, the accuracy
ENVELOPE vs bf16 (greedy-divergence-step + attention-output MAE — pinned
bounds, never bit-parity), bitwise pins where int8 must be exact
against ITSELF (prefix hit == miss, supervisor rebuild, fleet
migration, speculation on/off, run-to-run), the capacity-doubling
admission math under a byte budget, the exact per-dispatch byte model
on both decode impls (int8 <= 0.55x bf16), kv_dtype="auto" resolution
through the measured crossover store, chaos page exhaustion on a
quantized pool, and the zero-retrace guard with int8 + prefix cache +
speculation stacked."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import runtime
from deeplearning4j_tpu.resilience import chaos
from deeplearning4j_tpu.serving import (
    EngineSupervisor, GenerationEngine, PagedKVConfig, SpeculationConfig)
from deeplearning4j_tpu.serving.paged_kernel import (
    paged_attention, paged_attention_supported, paged_ref_attention)
from deeplearning4j_tpu.serving.quant import (
    KV_DTYPES, dequantize, kv_page_bytes, pool_leaves, pow2ceil,
    quantize)
from deeplearning4j_tpu.tuning.crossover import (
    KernelCrossoverStore, quant_fingerprint, reset_default_store)
from deeplearning4j_tpu.tuning.plan import resolve_kv_dtype
from deeplearning4j_tpu.util.decoding import prompt_lookup_proposer
from deeplearning4j_tpu.zoo import TextGenerationTransformer

V = 12
PROMPTS = [[1, 2, 3, 4, 5], [6, 7], [8, 9, 10, 1], [2, 4, 6], [3],
           [5, 5, 9]]

DIRECT_IMPLS = [
    pytest.param(dict(decode_impl="xla"), id="xla"),
    pytest.param(dict(decode_impl="pallas", kernel_interpret=True),
                 id="pallas-interpret"),
]


@pytest.fixture(scope="module")
def rope_model():
    return TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                     n_heads=2, n_layers=2,
                                     max_length=32, positional="rope")


@pytest.fixture(scope="module")
def rope_net(rope_model):
    return rope_model.init()


def drain(engine, handles):
    engine.run_until_idle()
    return [h.result(timeout=0) for h in handles]


def run_trace(net, prompts, steps=6, stagger=True, submit_kw=None,
              **engine_kw):
    eng = GenerationEngine(net, V, **engine_kw)
    hs = []
    for i, p in enumerate(prompts):
        hs.append(eng.submit(p, steps=steps,
                             rng=np.random.default_rng(i),
                             **(submit_kw or {})))
        if stagger:
            eng.step()
    return eng, drain(eng, hs)


def int8_cfg(**kw):
    return PagedKVConfig(page_size=4, kv_dtype="int8", **kw)


# ---------------------------------------------------------------------
# quantization primitives: the exactness the bitwise pins stand on
# ---------------------------------------------------------------------
class TestQuantPrimitives:
    def test_pow2ceil_exact(self):
        x = jnp.asarray([0.0, 1e-30, 0.3, 0.5, 1.0, 1.5, 2.0, 3.0,
                         100.0, 1024.0])
        got = np.asarray(pow2ceil(x))
        for xi, gi in zip(np.asarray(x), got):
            if xi == 0:
                assert gi == 0.0
                continue
            # a true power of two, >= x, and minimal (half is < x)
            m, e = np.frexp(gi)
            assert m == 0.5, (xi, gi)
            assert gi >= xi and gi / 2 < xi, (xi, gi)

    def test_roundtrip_bounded_by_half_sigma(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 128)) * 3, jnp.float32)
        sigma = pow2ceil(jnp.max(jnp.abs(x)) / 127.0)
        back = dequantize(quantize(x, sigma), sigma)
        err = np.abs(np.asarray(back) - np.asarray(x))
        assert (err <= float(sigma) / 2 + 1e-7).all()

    def test_zero_sigma_quantizes_to_zero(self):
        x = jnp.zeros((4, 8), jnp.float32)
        q = quantize(x, pow2ceil(jnp.max(jnp.abs(x)) / 127.0))
        assert (np.asarray(q) == 0).all()
        # and a nonzero input under sigma=0 (an all-zero page base)
        # must not divide by zero
        q2 = quantize(jnp.ones((4, 8)), jnp.zeros(()))
        assert (np.asarray(q2) == 0).all()

    def test_dequant_exact_in_bf16(self):
        """|q| <= 127 times a power of two is exactly representable in
        bf16 (7 mantissa bits) — the reason a bf16-native net's reads
        are bit-stable across dispatches."""
        q = jnp.arange(-127, 128, dtype=jnp.int8)
        for sig in (0.25, 1.0, 8.0):
            f32 = dequantize(q, sig, jnp.float32)
            b16 = dequantize(q, sig, jnp.bfloat16)
            np.testing.assert_array_equal(
                np.asarray(f32), np.asarray(b16.astype(jnp.float32)))

    def test_kv_page_bytes_and_pool_leaves(self):
        # one layer, Hkv=2, D=8, ps=4: int8 page = 2*(2*4*8*1 + 2*4)
        assert kv_page_bytes([(2, 8)], 4, "int8", "float32") == \
            2 * (2 * 4 * 8 + 2 * 4)
        assert kv_page_bytes([(2, 8)], 4, "bf16", "float32") == \
            2 * (2 * 4 * 8 * 4)
        assert kv_page_bytes([(2, 8)], 4, "bf16", "bfloat16") == \
            2 * (2 * 4 * 8 * 2)
        pools, scales = pool_leaves(5, 4, [(2, 8), (2, 8)])
        assert len(pools) == len(scales) == 4      # k and v per layer
        assert all(p.shape == (5, 2, 4, 8) and p.dtype == jnp.int8
                   for p in pools)
        assert all(s.shape == (5, 2) and s.dtype == jnp.float32
                   for s in scales)

    def test_kv_dtypes_vocabulary(self):
        assert KV_DTYPES == ("bf16", "int8", "auto")


# ---------------------------------------------------------------------
# the two readers over an int8 pool: envelope vs exact, kernel vs ref
# ---------------------------------------------------------------------
def _quantized_case(seed=0, S=3, hkv=2, reps=2, qw=1, d=8, ps=4, nb=5):
    rng = np.random.default_rng(seed)
    P = S * nb + 1
    rw = reps * qw
    q = jnp.asarray(rng.normal(size=(S, hkv, rw, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, hkv, ps, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, hkv, ps, d)), jnp.float32)
    table = jnp.asarray(
        rng.permutation(np.arange(1, P))[:S * nb].reshape(S, nb),
        jnp.int32)
    lengths = jnp.asarray(rng.integers(qw, nb * ps + 1, S), jnp.int32)
    # per-(page, head) quantization, the pool scheme
    ks = pow2ceil(jnp.max(jnp.abs(kp), axis=(2, 3)) / 127.0)
    vs = pow2ceil(jnp.max(jnp.abs(vp), axis=(2, 3)) / 127.0)
    kq = quantize(kp, ks[:, :, None, None])
    vq = quantize(vp, vs[:, :, None, None])
    return q, kp, vp, kq, vq, ks, vs, table, lengths


class TestQuantReaders:
    def test_ref_attention_mae_envelope(self):
        """The accuracy contract is an ENVELOPE: int8 pools through the
        dense-gather reference stay within a pinned MAE of the exact
        pools — and are NOT bit-identical (the quantization is real)."""
        (q, kp, vp, kq, vq, ks, vs, table,
         lengths) = _quantized_case()
        exact = paged_ref_attention(q, kp, vp, table, lengths,
                                    query_width=1)
        kd = dequantize(kq, ks[:, :, None, None])
        vd = dequantize(vq, vs[:, :, None, None])
        quant = paged_ref_attention(q, kd, vd, table, lengths,
                                    query_width=1)
        diff = np.abs(np.asarray(exact) - np.asarray(quant))
        assert diff.mean() <= 0.02
        assert diff.max() <= 0.1
        assert diff.max() > 0          # a real quantizer, not a no-op

    @pytest.mark.parametrize("qw", [1, 3])
    def test_kernel_matches_dequantized_reference(self, qw):
        """The int8 kernel IS dequant(int8) attention: per-page scales
        commute with both dots, so its output equals the reference run
        on the dequantized pools (float tolerance, both widths)."""
        (q, _, _, kq, vq, ks, vs, table,
         lengths) = _quantized_case(qw=qw)
        out = paged_attention(q, kq, vq, table, lengths,
                              query_width=qw, interpret=True,
                              k_scales=ks, v_scales=vs)
        kd = dequantize(kq, ks[:, :, None, None])
        vd = dequantize(vq, vs[:, :, None, None])
        ref = paged_ref_attention(q, kd, vd, table, lengths,
                                  query_width=qw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_scales_travel_together_and_need_int8(self):
        (q, kp, _, kq, vq, ks, vs, table,
         lengths) = _quantized_case()
        with pytest.raises(ValueError, match="together"):
            paged_attention(q, kq, vq, table, lengths, query_width=1,
                            interpret=True, k_scales=ks)
        with pytest.raises(ValueError, match="int8"):
            paged_attention(q, kp, kp, table, lengths, query_width=1,
                            interpret=True, k_scales=ks, v_scales=vs)

    def test_supported_gate_tightens_for_int8(self):
        assert paged_attention_supported((0, 0, 32, 128), 1,
                                         kv_dtype="int8")
        assert not paged_attention_supported((0, 0, 8, 128), 1,
                                             kv_dtype="int8")
        assert not paged_attention_supported((0, 0, 32, 64), 1,
                                             kv_dtype="int8")
        # the bf16 gate is unchanged
        assert paged_attention_supported((0, 0, 8, 64), 1)


# ---------------------------------------------------------------------
# engine accuracy envelope + determinism pins
# ---------------------------------------------------------------------
class TestInt8Engine:
    def _greedy(self, net, kv_dtype, steps=10, **impl):
        _, got = run_trace(
            net, PROMPTS, steps=steps, slots=3, stagger=False,
            submit_kw=dict(top_k=1),
            paging=PagedKVConfig(page_size=4, kv_dtype=kv_dtype,
                                 **impl))
        return got

    def test_greedy_divergence_envelope(self, rope_net):
        """The pinned accuracy envelope: greedy int8 streams track the
        bf16 streams for at least the first generated tokens, and most
        prompts never diverge at all on this model. NOT a bit-parity
        claim — the pins are the envelope."""
        g16 = self._greedy(rope_net, "bf16")
        g8 = self._greedy(rope_net, "int8")
        divergence = []
        for a, b, p in zip(g16, g8, PROMPTS):
            gen_a, gen_b = a[len(p):], b[len(p):]
            divergence.append(next(
                (i for i, (x, y) in enumerate(zip(gen_a, gen_b))
                 if x != y), len(gen_a)))
        assert min(divergence) >= 2, divergence
        assert sum(d == 10 for d in divergence) >= 2, divergence

    @pytest.mark.parametrize("impl", DIRECT_IMPLS)
    def test_deterministic_run_to_run(self, rope_net, impl):
        """Same engine config, same rngs, twice: identical sampled
        streams — quantized pool bytes are a pure function of the
        committed token stream."""
        kw = dict(steps=7, slots=3,
                  submit_kw=dict(temperature=0.9, top_p=0.9),
                  paging=PagedKVConfig(page_size=4, kv_dtype="int8",
                                       **impl))
        _, a = run_trace(rope_net, PROMPTS, **kw)
        _, b = run_trace(rope_net, PROMPTS, **kw)
        assert a == b

    def test_xla_and_kernel_agree_token_level(self, rope_net):
        """Both int8 readers dequantize the same pool bytes: greedy
        streams agree across the folded-gather and kernel impls (the
        same cross-impl pin the bf16 suite holds sampled)."""
        xla = self._greedy(rope_net, "int8", decode_impl="xla")
        kern = self._greedy(rope_net, "int8", decode_impl="pallas",
                            kernel_interpret=True)
        assert xla == kern

    @pytest.mark.parametrize("impl", DIRECT_IMPLS)
    def test_speculation_bit_identical_to_plain(self, rope_net, impl):
        """Speculative rewind re-prices pages deterministically: int8 +
        speculation streams equal plain int8 streams bit for bit (the
        wide verify writes the same base tokens at the same values, so
        the same scales)."""
        prompts = [[1, 2, 3, 1, 2], [4, 5, 4, 5], [7, 8, 7]]
        kw = dict(steps=8, slots=3, submit_kw=dict(top_k=1),
                  paging=PagedKVConfig(page_size=4, kv_dtype="int8",
                                       **impl))
        _, plain = run_trace(rope_net, prompts, **kw)
        _, spec = run_trace(
            rope_net, prompts,
            speculation=SpeculationConfig(
                draft=prompt_lookup_proposer(2), gamma=2), **kw)
        assert spec == plain

    def test_recurrent_net_refused(self):
        """A hybrid net (attention KV + LSTM h/c) passes the paging
        gate but must refuse int8: recurrent state cannot re-prime
        through the paged path."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            Convolution1DLayer, GravesLSTM, RnnOutputLayer,
            SelfAttentionLayer)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .weight_init("xavier")
                .graph_builder().add_inputs("in")
                .set_input_types(InputType.recurrent(V, 32))
                .add_layer("embed", Convolution1DLayer(
                    n_out=16, kernel=1, convolution_mode="same",
                    activation="identity"), "in")
                .add_layer("attn", SelfAttentionLayer(
                    n_out=16, n_heads=2, causal=True, cache_length=32,
                    rope=True, activation="identity"), "embed")
                .add_layer("rnn", GravesLSTM(n_out=16), "attn")
                .add_layer("out", RnnOutputLayer(
                    n_out=V, loss="mcxent", activation="softmax"),
                    "rnn")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        with pytest.raises(ValueError, match="recurrent"):
            GenerationEngine(net, V, slots=2,
                             paging=int8_cfg(prefix_cache=False))
        # the same net serves fine unquantized
        eng = GenerationEngine(
            net, V, slots=2,
            paging=PagedKVConfig(page_size=4, prefix_cache=False))
        h = eng.submit([1, 2, 3], steps=3, top_k=1,
                       rng=np.random.default_rng(0))
        assert drain(eng, [h])[0]

    def test_int8_requires_direct(self):
        with pytest.raises(ValueError, match="direct"):
            PagedKVConfig(kv_dtype="int8", direct=False)

    def test_bad_kv_dtype_rejected(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            PagedKVConfig(kv_dtype="fp8")


# ---------------------------------------------------------------------
# bitwise pins: prefix hit == miss, rebuild, migration
# ---------------------------------------------------------------------
class TestInt8PrefixAndRecovery:
    SHARED = [3, 1, 2, 0] * 2                  # two full ps=4 blocks
    PROMPTS3 = [SHARED + [5], SHARED + [7, 8], [9, 9]]

    def _run(self, net, **kw):
        eng = GenerationEngine(net, V, slots=2, **kw)
        hs = [eng.submit(p, steps=5, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(self.PROMPTS3)]
        return eng, drain(eng, hs)

    @pytest.mark.parametrize("impl", DIRECT_IMPLS)
    def test_prefix_hit_equals_miss_bitwise(self, rope_net, impl):
        """A prefix-cache hit re-reads the EXACT int8 bytes + scales
        the inserting prime wrote, and the suffix prime starts past
        them — hit streams equal fresh-prefill streams bit for bit
        (power-of-two scales make the dequantized read a pure function
        of the committed tokens)."""
        _, miss = self._run(rope_net,
                            paging=int8_cfg(prefix_cache=False, **impl))
        eng, hit = self._run(rope_net, paging=int8_cfg(**impl))
        assert eng.prefix_cache.hits > 0
        assert hit == miss

    @pytest.mark.parametrize("impl", DIRECT_IMPLS)
    def test_rebuild_bit_identical(self, rope_net, impl):
        """Supervisor quarantine on an int8 arena: fresh zeroed pools +
        scales, every survivor re-primes THROUGH the quantized paged
        path — streams continue bit-identical to an unperturbed int8
        run."""
        _, want = self._run(rope_net, paging=int8_cfg(**impl))
        sup = EngineSupervisor()
        eng, got = self._run(
            rope_net, paging=int8_cfg(**impl), supervisor=sup,
            decode_chaos=chaos.FaultBurstInjector(n=3, k=1))
        assert got == want
        assert sup.rebuilds == 1 and eng.is_healthy()
        assert eng.health()["kv_traffic"]["kv_dtype"] == "int8"

    def test_migration_continues_bit_identical(self, rope_net):
        """The ledger hop (fleet migration): actives exported from one
        int8 engine re-prime on another and continue bit-identical —
        the pool bytes are reproducible from the ledger alone."""
        _, want = self._run(rope_net, paging=int8_cfg())
        src = GenerationEngine(rope_net, V, slots=2, paging=int8_cfg())
        hs = [src.submit(p, steps=5, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(self.PROMPTS3)]
        for _ in range(3):
            src.step()
        entries = src.export_ledger(include_queued=True)
        dst = GenerationEngine(rope_net, V, slots=2, paging=int8_cfg())
        took = dst.admit_from_ledger(entries, where="test migration")
        assert took == len(entries)
        dst.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want


# ---------------------------------------------------------------------
# capacity: the same byte budget admits ~2x the pages
# ---------------------------------------------------------------------
class TestInt8Capacity:
    def test_total_bytes_doubles_pages(self, rope_net):
        """Exact admission math under a byte budget: pages = budget //
        page_bytes with the scale sidecar priced in — and the int8
        pool admits at least 2x the bf16 pages (4x against an f32-
        native net: this model's 'bf16' pool stores f32 leaves)."""
        budget = 200_000
        engines = {}
        for dt in ("int8", "bf16"):
            engines[dt] = GenerationEngine(
                rope_net, V, slots=2,
                paging=PagedKVConfig(page_size=4, kv_dtype=dt,
                                     total_bytes=budget))
        dims = [(h, d) for _, h, d in engines["int8"]._quant_dims]
        for dt, eng in engines.items():
            per_page = kv_page_bytes(dims, 4, dt, "float32")
            assert eng.page_pool.usable == budget // per_page, dt
        assert engines["int8"].page_pool.usable >= \
            2 * engines["bf16"].page_pool.usable

    def test_capacity_knobs_exclusive(self):
        with pytest.raises(ValueError, match="at most one"):
            PagedKVConfig(total_bytes=1000, total_pages=4)
        with pytest.raises(ValueError, match="total_bytes"):
            PagedKVConfig(total_bytes=0)

    def test_budget_smaller_than_one_page_refused(self, rope_net):
        with pytest.raises(ValueError, match="no page"):
            GenerationEngine(rope_net, V, slots=2,
                             paging=PagedKVConfig(page_size=4,
                                                  total_bytes=10))

    def test_int8_capacity_serves_more_tokens(self, rope_net):
        """The point of the halving: a budget that head-blocks bf16
        admits the same work under int8."""
        dims = [(2, 8)] * 2
        budget = 12 * kv_page_bytes(dims, 4, "bf16", "float32")
        long_prompt = list(np.random.default_rng(0).integers(1, V, 20))
        eng8 = GenerationEngine(
            rope_net, V, slots=2,
            paging=PagedKVConfig(page_size=4, kv_dtype="int8",
                                 total_bytes=budget,
                                 prefix_cache=False))
        # 12 bf16 pages buy ~3.5x pages under int8 -> two long streams
        hs = [eng8.submit(long_prompt + [i], steps=6, top_k=1,
                          rng=np.random.default_rng(i))
              for i in range(2)]
        got = drain(eng8, hs)
        assert all(len(g) == 27 for g in got)


# ---------------------------------------------------------------------
# the byte model: int8 halves the bytes the dispatch moves
# ---------------------------------------------------------------------
class TestInt8Traffic:
    def _steady_step_bytes(self, net, paging, slots=2):
        eng = GenerationEngine(net, V, slots=slots, paging=paging)
        h = eng.submit([1, 2, 3], steps=8, top_k=1,
                       rng=np.random.default_rng(0))
        eng.step()                           # admission + first decode
        before = eng._kv_bytes_total
        eng.step()                           # pure decode
        per_step = eng._kv_bytes_total - before
        eng.shutdown()
        return per_step, eng

    def test_byte_model_exact_and_halved_both_impls(self, rope_net):
        """The mechanism pin (not wall-clock): exact per-dispatch byte
        formulas under int8 — pool terms at 1 byte/element plus the
        scale-sidecar reads — and int8 <= 0.55x bf16 on BOTH impls."""
        legs = {}
        for dt in ("bf16", "int8"):
            for impl in ("xla", "pallas"):
                kw = (dict(decode_impl="pallas", kernel_interpret=True)
                      if impl == "pallas" else dict(decode_impl="xla"))
                legs[dt, impl] = self._steady_step_bytes(
                    rope_net, PagedKVConfig(page_size=4, kv_dtype=dt,
                                            **kw))
        for impl in ("xla", "pallas"):
            per8, e8 = legs["int8", impl]
            per16, e16 = legs["bf16", impl]
            tok8, tok16 = e8._tok_bytes, e16._tok_bytes
            assert tok8 * 4 == tok16         # f32-native net: 4 -> 1 B
            S, L, ps, nm = e8.slots, e8._L, e8._ps, e8._n_max
            row = e8._scale_row_bytes
            assert row == 2 * 2 * 2 * 4      # 2 layers x k,v x Hkv x f32
            assert e16._scale_row_bytes == 0
            if impl == "xla":
                assert per16 == S * L * tok16 + S * tok16
                assert per8 == S * L * tok8 + S * tok8 + S * nm * row
            else:
                # one active row at position 4: one page-rounded live
                # read (8 positions = 2 pages) + the all-rows append
                assert per16 == 8 * tok16 + S * tok16
                assert per8 == 8 * tok8 + S * tok8 + 2 * row
            assert per8 <= 0.55 * per16, impl

    def test_health_reports_kv_dtype(self, rope_net):
        eng8 = GenerationEngine(rope_net, V, slots=2, paging=int8_cfg())
        assert eng8.health()["kv_traffic"]["kv_dtype"] == "int8"
        eng16 = GenerationEngine(rope_net, V, slots=2,
                                 paging=PagedKVConfig(page_size=4))
        assert eng16.health()["kv_traffic"]["kv_dtype"] == "bf16"


# ---------------------------------------------------------------------
# kv_dtype="auto": opted into by a calibrated measurement
# ---------------------------------------------------------------------
class TestAutoResolution:
    KEY_KW = dict(page_size=4, head_dim=8, n_kv_heads=2,
                  cache_length=32)

    def _store(self, entries=None):
        return KernelCrossoverStore(path="/nonexistent/none",
                                    entries=entries or {})

    def test_uncalibrated_resolves_bf16(self, rope_net):
        reset_default_store(self._store())
        try:
            eng = GenerationEngine(
                rope_net, V, slots=2,
                paging=PagedKVConfig(page_size=4, kv_dtype="auto"))
            assert eng._kv_dtype == "bf16"
            assert eng._quant_key == quant_fingerprint(
                dtype="float32", **self.KEY_KW)
        finally:
            reset_default_store(None)

    def test_calibrated_win_resolves_int8(self, rope_net):
        key = quant_fingerprint(dtype="float32", **self.KEY_KW)
        s = self._store()
        s.record(key, 1.0, 2.5)       # int8 leg measured 2.5x faster
        reset_default_store(s)
        try:
            eng = GenerationEngine(
                rope_net, V, slots=2,
                paging=PagedKVConfig(page_size=4, kv_dtype="auto"))
            assert eng._kv_dtype == "int8"
            # and it actually serves quantized
            h = eng.submit([1, 2, 3], steps=3, top_k=1,
                           rng=np.random.default_rng(0))
            assert drain(eng, [h])[0]
            assert eng.health()["kv_traffic"]["kv_dtype"] == "int8"
        finally:
            reset_default_store(None)

    def test_platform_mismatch_refused(self, rope_net):
        """A TPU-calibrated win must not turn int8 on for CPU runs —
        the store's platform guard applies to quant entries too."""
        key = quant_fingerprint(dtype="float32", **self.KEY_KW)
        s = self._store(entries={key: {
            "kernel_ms": 1.0, "fallback_ms": 2.5, "platform": "tpu",
            "device_kind": "TPU v4", "impl_rev": 1, "samples": 1}})
        reset_default_store(s)
        try:
            eng = GenerationEngine(
                rope_net, V, slots=2,
                paging=PagedKVConfig(page_size=4, kv_dtype="auto"))
            assert eng._kv_dtype == "bf16"
        finally:
            reset_default_store(None)

    def test_resolver_ineligible_is_bf16(self):
        assert resolve_kv_dtype(False, "paged_decode_quant|x|f32",
                                store=self._store()) == "bf16"
        s = self._store()
        s.record("paged_decode_quant|x|f32", 1.0, 2.0)
        assert resolve_kv_dtype(True, "paged_decode_quant|x|f32",
                                store=s) == "int8"
        assert resolve_kv_dtype(True, "paged_decode_quant|x|f32",
                                store=self._store()) == "bf16"


# ---------------------------------------------------------------------
# chaos: page exhaustion on a quantized pool
# ---------------------------------------------------------------------
class TestInt8Chaos:
    def test_page_exhaustion_actives_bit_identical(self, rope_net):
        """Seizing an int8 pool's free pages (scale sidecar rows travel
        implicitly with the page ids — host accounting only) starves
        new admissions while actives complete bit-identical to an
        unperturbed int8 run, and release un-blocks the stragglers."""
        kw = dict(steps=6, slots=3, stagger=False,
                  submit_kw=dict(top_k=1))
        _, want = run_trace(rope_net, PROMPTS[:2],
                            paging=int8_cfg(total_pages=6,
                                            prefix_cache=False), **kw)
        _, want_late = run_trace(rope_net, [[4, 5, 6]], steps=21,
                                 slots=3, stagger=False,
                                 submit_kw=dict(top_k=1),
                                 paging=int8_cfg(total_pages=6,
                                                 prefix_cache=False))
        eng = GenerationEngine(
            rope_net, V, slots=3,
            paging=int8_cfg(total_pages=6, prefix_cache=False))
        inj = chaos.PageExhaustionInjector(eng.page_pool, n=1,
                                           free_target=0)
        eng._decode_chaos = inj
        hs = [eng.submit(p, steps=6, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:2])]
        eng.step()
        eng.step()                        # injector fires: free -> 0
        assert eng.page_pool.free_count() == 0
        late = eng.submit([4, 5, 6], steps=21, top_k=1,
                          rng=np.random.default_rng(0))
        eng.step()
        assert eng.queue_depth() == 1     # head-blocked, not admitted
        got = drain(eng, hs)
        assert got == want
        assert not late.done
        inj.release()
        eng.run_until_idle()
        assert late.result(timeout=0) == want_late[0]


# ---------------------------------------------------------------------
# zero retraces after warmup with int8 + prefix + speculation
# ---------------------------------------------------------------------
def _compile_total():
    c = monitoring.global_registry().get(runtime.COMPILE_COUNTER)
    return 0.0 if c is None else c.total()


class TestInt8NoRetrace:
    def test_compiles_nothing_after_warmup(self):
        monitoring.ensure_started()
        model = TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=64,
                                          positional="rope")
        net = model.init()
        eng = GenerationEngine(
            net, V, slots=4,
            paging=PagedKVConfig(page_size=8, kv_dtype="int8"),
            speculation=SpeculationConfig(
                draft=prompt_lookup_proposer(2), gamma=3))
        eng.warmup(max_prompt_len=16)
        warm = _compile_total()
        SYS = [7, 3, 9, 1, 4, 2, 8, 5]
        rng = np.random.default_rng(0)
        hs = []
        for i in range(12):
            n = int(rng.integers(1, 16))
            p = (SYS + list(rng.integers(1, V, n - 8))
                 if i % 2 and n > 8 else list(rng.integers(1, V, n)))
            hs.append(eng.submit(p, steps=int(rng.integers(2, 10)),
                                 top_k=1, rng=np.random.default_rng(i)))
            eng.step()
        eng.run_until_idle()
        assert all(h.done for h in hs)
        assert eng.prefix_cache.hits > 0
        assert _compile_total() == warm, (
            "int8 paged decode retraced after warmup")
