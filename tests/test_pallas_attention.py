"""Pallas flash attention vs reference math (backend-vs-backend pattern,
the ValidateCudnnLSTM.java role for the attention hot op).

Runs the kernel in interpreter mode on CPU: same kernel code path the TPU
compiles, exactness asserted against reference_attention and jax.grad
through it. Real-chip perf lives in bench_all.py / PERF.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.pallas_attention import (
    flash_attention, flash_attention_supported,
)
from deeplearning4j_tpu.parallel.sequence import reference_attention


def _qkv(B=2, H=2, T=256, D=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((B, H, T, D)) * 0.5, dtype)
    return mk(), mk(), mk()


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128, interpret=True)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_unequal_blocks(self):
        q, k, v = _qkv(T=512)
        out = flash_attention(q, k, v, causal=True, block_q=256,
                              block_k=128, interpret=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_ragged_t_multi_block_padding_noncausal(self):
        # unequal blocks pad T to lcm(bq,bk)=256, so padded keys span TWO
        # KV blocks (300->512, blocks j=2,3 at bk=128); every padded block
        # must take the masked path, not just the last one
        q, k, v = _qkv(T=300)
        out = flash_attention(q, k, v, causal=False, block_q=256,
                              block_k=128, interpret=True)
        ref = reference_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_ragged_t_padding(self):
        # T not a multiple of the block: padded internally, sliced back
        q, k, v = _qkv(T=200)
        out = flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=128, interpret=True)
        ref = reference_attention(q, k, v, causal=True)
        assert out.shape == q.shape
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_key_mask(self):
        B, T = 2, 256
        q, k, v = _qkv(B=B, T=T)
        rng = np.random.default_rng(3)
        lengths = rng.integers(T // 4, T, B)
        km = jnp.asarray(np.arange(T)[None, :] < lengths[:, None],
                         jnp.float32)
        out = flash_attention(q, k, v, key_mask=km, block_q=128,
                              block_k=128, interpret=True)
        # reference: NEG_INF-mask the padded keys
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        s = jnp.where(km[:, None, None, :] > 0, s, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_causal_with_key_mask(self):
        # both mask sources at once: causal triangle AND variable-length
        # keys (the user_mask path folds the causal test into _block_mask)
        B, T = 2, 256
        q, k, v = _qkv(B=B, T=T, seed=21)
        lengths = np.array([200, 120])
        km = jnp.asarray(np.arange(T)[None, :] < lengths[:, None],
                         jnp.float32)
        out = flash_attention(q, k, v, causal=True, key_mask=km,
                              block_q=128, block_k=128, interpret=True)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        tri = jnp.tril(jnp.ones((T, T), bool))
        valid = tri[None, None] & (km[:, None, None, :] > 0)
        s = jnp.where(valid, s, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        # rows with zero valid keys (q_pos >= length under causal can't
        # happen: position i always sees key i... unless i >= length):
        # those rows are undefined in the naive ref too — compare only
        # rows with at least one valid key
        H = q.shape[1]
        row_ok = np.broadcast_to(np.asarray(valid.any(axis=-1)),
                                 (B, H, T))
        got, want = np.asarray(out), np.asarray(ref)
        np.testing.assert_allclose(got[row_ok], want[row_ok],
                                   atol=2e-5, rtol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = _qkv(dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=128, interpret=True)
        ref = reference_attention(q.astype(jnp.float32),
                                  k.astype(jnp.float32),
                                  v.astype(jnp.float32), causal=True)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref,
                                   atol=3e-2, rtol=3e-2)

    def test_supported_gate(self):
        assert flash_attention_supported((2, 4, 1024, 128))
        assert flash_attention_supported((2, 4, 1024, 64))
        assert not flash_attention_supported((2, 4, 1024, 80))
        assert not flash_attention_supported((2, 4, 32, 64))
        assert not flash_attention_supported((4, 1024, 128))


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(B=1, H=2, T=256, D=64, seed=7)
        tgt = jnp.asarray(
            np.random.default_rng(9).standard_normal(q.shape), jnp.float32)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=128,
                                block_k=128, interpret=True)
            return jnp.sum((o - tgt) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum((reference_attention(q, k, v, causal=causal)
                            - tgt) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_grads_with_ragged_t(self):
        q, k, v = _qkv(B=1, H=1, T=200, D=64, seed=11)

        def loss_flash(q):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           block_q=128, block_k=128,
                                           interpret=True) ** 2)

        def loss_ref(q):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        np.testing.assert_allclose(jax.grad(loss_flash)(q),
                                   jax.grad(loss_ref)(q),
                                   atol=5e-4, rtol=5e-4)

    def test_zero_length_row_grads_finite(self):
        # a batch row whose key_mask is all zeros must not NaN the grads
        # (masked raw scores above the row lse would overflow exp if the
        # backward kernels exponentiated unmasked scores)
        B, T = 2, 128
        q, k, v = _qkv(B=B, T=T, seed=17)
        km = jnp.stack([jnp.ones((T,)), jnp.zeros((T,))]).astype(jnp.float32)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, key_mask=km,
                                           block_q=128, block_k=128,
                                           interpret=True) ** 2)

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g)))

    def test_grads_with_key_mask(self):
        B, T = 2, 128
        q, k, v = _qkv(B=B, T=T, seed=13)
        km = jnp.asarray(np.arange(T)[None, :] < np.array([100, 64])[:, None],
                         jnp.float32)

        def loss_flash(k, v):
            return jnp.sum(flash_attention(q, k, v, key_mask=km,
                                           block_q=128, block_k=128,
                                           interpret=True) ** 2)

        def loss_ref(k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
            s = jnp.where(km[:, None, None, :] > 0, s, -1e30)
            o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
            return jnp.sum(o ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1))(k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1))(k, v)
        for a, b, name in zip(gf, gr, ("k", "v")):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")


class TestWindowKernel:
    """Sliding-window block skipping in the flash kernel."""

    def _masked_ref(self, q, k, v, W):
        T = q.shape[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        idx = jnp.arange(T)
        valid = (idx[:, None] >= idx[None, :]) & \
                (idx[:, None] - idx[None, :] < W)
        s = jnp.where(valid[None, None], s, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    @pytest.mark.parametrize("W", [64, 128, 200])
    def test_matches_masked_reference(self, W):
        q, k, v = _qkv(T=512, seed=41)
        out = flash_attention(q, k, v, causal=True, window=W,
                              block_q=128, block_k=128, interpret=True)
        np.testing.assert_allclose(out, self._masked_ref(q, k, v, W),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_masked_reference(self):
        q, k, v = _qkv(B=1, H=1, T=256, D=64, seed=43)
        W = 96

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True, window=W,
                                           block_q=128, block_k=128,
                                           interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(self._masked_ref(q, k, v, W) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name}")

    def test_window_with_key_mask(self):
        B, T, W = 2, 256, 64
        q, k, v = _qkv(B=B, T=T, seed=45)
        km = jnp.asarray(np.arange(T)[None, :] <
                         np.array([220, 130])[:, None], jnp.float32)
        out = flash_attention(q, k, v, causal=True, window=W, key_mask=km,
                              block_q=128, block_k=128, interpret=True)
        idx = jnp.arange(T)
        valid = (idx[:, None] >= idx[None, :]) & \
                (idx[:, None] - idx[None, :] < W)
        valid = valid[None, None] & (km[:, None, None, :] > 0)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        s = jnp.where(valid, s, -1e30)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        row_ok = np.broadcast_to(np.asarray(valid.any(-1)),
                                 (B, q.shape[1], T))
        np.testing.assert_allclose(np.asarray(out)[row_ok],
                                   np.asarray(ref)[row_ok],
                                   atol=2e-5, rtol=2e-5)

    def test_scan_and_kernel_agree(self):
        from deeplearning4j_tpu.parallel.sequence import blockwise_attention
        q, k, v = _qkv(T=256, seed=47)
        a = blockwise_attention(q, k, v, causal=True, window=80,
                                use_pallas=False)
        b = flash_attention(q, k, v, causal=True, window=80,
                            block_q=128, block_k=128, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)

    def test_noncausal_window_rejected(self):
        q, k, v = _qkv(T=128)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=32,
                            interpret=True)


class TestDecodeShapes:
    """Decode-shaped queries (PR 10, the serving fast path): a width-1
    or width-1+gamma query block attending a long KV prefix as banded
    attention with q_offset = Tk - W — the flash-kernel shape the
    engine's dispatch family maps onto (the paged pool variant lives in
    serving/paged_kernel.py, pinned by its own suite; this pins the
    dense-KV kernel at the same query widths)."""

    @staticmethod
    def _decode_ref(q, k, v, W):
        # query w sits at absolute position Tk - W + w
        Tk = k.shape[2]
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
        qpos = Tk - W + jnp.arange(W)
        valid = jnp.arange(Tk)[None, :] <= qpos[:, None]
        s = jnp.where(valid[None, None], s, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1),
                          v.astype(jnp.float32))

    @pytest.mark.parametrize("W", [1, 5])
    def test_decode_width_matches_reference(self, W):
        from deeplearning4j_tpu.nn.layers.pallas_attention import (
            flash_attention_lse)
        rng = np.random.default_rng(11)
        B, H, Tk, D = 2, 2, 384, 64
        q = jnp.asarray(rng.standard_normal((B, H, W, D)) * 0.5,
                        jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, H, Tk, D)) * 0.5,
                        jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, H, Tk, D)) * 0.5,
                        jnp.float32)
        out, lse = flash_attention_lse(q, k, v, causal=True,
                                       q_offset=Tk - W,
                                       block_q=128, block_k=128,
                                       interpret=True)
        ref = self._decode_ref(q, k, v, W)
        assert out.shape == (B, H, W, D)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
        # the lse is finite and real for every decode row (the ring /
        # cross-chunk combine contract holds at decode widths too)
        assert np.isfinite(np.asarray(lse)).all()

    def test_decode_width_sees_only_past(self):
        """Poison the keys strictly after the LAST query's position
        with large FINITE garbage (the kernel's masking contract — the
        dense arena's idle-slot argument: masked scores go to -1e30
        before the softmax, and zero probabilities annihilate finite
        values exactly): a decode-shaped block must not read them."""
        from deeplearning4j_tpu.nn.layers.pallas_attention import (
            flash_attention_lse)
        rng = np.random.default_rng(13)
        B, H, Tk, D, W = 1, 2, 256, 64, 3
        q = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.float32)
        k = np.asarray(rng.standard_normal((B, H, Tk, D)), np.float32)
        v = np.asarray(rng.standard_normal((B, H, Tk, D)), np.float32)
        # run the appended chunk mid-sequence: keys past off+W are
        # visible to NO real query row
        off = 100
        kp, vp = k.copy(), v.copy()
        kp[:, :, off + W:] = 1e6
        vp[:, :, off + W:] = 1e6
        a, _ = flash_attention_lse(jnp.asarray(q),
                                   jnp.asarray(k[:, :, :off + W]),
                                   jnp.asarray(v[:, :, :off + W]),
                                   causal=True, q_offset=off,
                                   block_q=128, block_k=128,
                                   interpret=True)
        b, _ = flash_attention_lse(jnp.asarray(q), jnp.asarray(kp),
                                   jnp.asarray(vp), causal=True,
                                   q_offset=off, block_q=128,
                                   block_k=128, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
