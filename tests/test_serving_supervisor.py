"""Serving survivability (serving/supervisor.py, serving/overload.py,
engine recovery/drain): request-preserving arena rebuilds bit-identical
to an unperturbed run (greedy + sampled, slot + paged arenas, prefix
cache + speculation on), restart-budget escalation to the terminal
fail-all, the pop-to-seat handoff window, SLO shedding, deadline-based
early rejection, the brownout ladder, draining, and the
zero-retraces-after-recovery guard."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import runtime
from deeplearning4j_tpu.monitoring.metrics import MetricsRegistry
from deeplearning4j_tpu.resilience import chaos
from deeplearning4j_tpu.resilience.retry import RestartBudget
from deeplearning4j_tpu.serving import (
    AdmissionQueue, EngineShutdown, EngineSupervisor, GenerationEngine,
    GenerationRequest, InferenceTimeout, OverloadConfig, PagedKVConfig,
    RequestCancelled, ServingOverloaded, SpeculationConfig)
from deeplearning4j_tpu.serving.health import (
    SERVING_BROWNOUT_LEVEL, SERVING_DRAINING,
    SERVING_ENGINE_ESCALATIONS, SERVING_ENGINE_REBUILDS,
    SERVING_RECOVERED_REQUESTS, SERVING_SHED)
from deeplearning4j_tpu.serving.overload import OverloadController
from deeplearning4j_tpu.util.decoding import prompt_lookup_proposer
from deeplearning4j_tpu.zoo import TextGenerationTransformer

V = 12
PROMPTS = [[1, 2, 3, 4, 5], [6, 7], [8, 9, 10, 1], [2, 4, 6]]


@pytest.fixture(scope="module")
def rope_net():
    return TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                     n_heads=2, n_layers=2,
                                     max_length=32,
                                     positional="rope").init()


def _run(net, prompts=None, steps=5, sampled=False, n_slots=2, **kw):
    """Drive a trace to completion on a fresh engine; returns
    (engine, handles)."""
    eng = GenerationEngine(net, V, slots=n_slots, **kw)
    hs = []
    for i, p in enumerate(prompts or PROMPTS[:3]):
        s = (dict(temperature=1.3, top_p=0.9) if sampled
             else dict(top_k=1))
        hs.append(eng.submit(p, steps=steps,
                             rng=np.random.default_rng(i), **s))
    eng.run_until_idle()
    return eng, hs


def _outs(handles):
    return [h.result(timeout=0) for h in handles]


# ---------------------------------------------------------------------
# the acceptance bar: a mid-stream decode fault recovers every in-flight
# request bit-identical to an unperturbed run
# ---------------------------------------------------------------------
class TestSupervisedRecovery:
    def test_greedy_slot_arena_recovers_bit_identical(self, rope_net):
        _, base = _run(rope_net)
        want = _outs(base)
        sup = EngineSupervisor(budget=RestartBudget(3, 60.0))
        eng, hs = _run(rope_net, supervisor=sup,
                       decode_chaos=chaos.FaultBurstInjector(n=2, k=1))
        assert _outs(hs) == want
        assert eng.is_healthy()
        assert sup.rebuilds == 1 and sup.recovered_requests >= 1
        assert sup.escalations == 0

    def test_sampled_recovers_bit_identical(self, rope_net):
        """The rng fast-forward is implicit: the per-request Generator
        lives host-side and a failed dispatch never drew from it, so
        re-priming from prompt + committed tokens continues SAMPLED
        streams exactly (not just greedy argmax chains)."""
        _, base = _run(rope_net, sampled=True)
        want = _outs(base)
        eng, hs = _run(rope_net, sampled=True,
                       supervisor=EngineSupervisor(),
                       decode_chaos=chaos.FaultBurstInjector(n=2, k=1))
        assert _outs(hs) == want
        assert eng.is_healthy()

    def test_paged_with_prefix_cache_recovers(self, rope_net):
        """Paged arena + prefix cache on: the rebuild re-creates pool,
        page tables, AND the prefix cache (re-seeded by the re-primes),
        and outputs stay bit-identical. Shared leading blocks make the
        post-rebuild re-primes exercise the cache-hit path too."""
        shared = [3, 1, 2, 0] * 2             # two full 4-token blocks
        prompts = [shared + [5], shared + [7, 8], [9, 9]]
        cfg = dict(prompts=prompts, paging=PagedKVConfig(page_size=4))
        _, base = _run(rope_net, **cfg)
        want = _outs(base)
        sup = EngineSupervisor()
        eng, hs = _run(rope_net, supervisor=sup,
                       decode_chaos=chaos.FaultBurstInjector(n=3, k=1),
                       **cfg)
        assert _outs(hs) == want
        assert eng.is_healthy() and sup.rebuilds == 1
        # fresh pool: no page leaked through the rebuild
        assert eng.page_pool.used_count() == len(eng.prefix_cache)

    def test_speculative_recovery(self, rope_net):
        cfg = dict(paging=PagedKVConfig(page_size=4),
                   speculation=SpeculationConfig(
                       draft=prompt_lookup_proposer(2), gamma=2))
        _, base = _run(rope_net, **cfg)
        want = _outs(base)
        eng, hs = _run(rope_net, supervisor=EngineSupervisor(),
                       decode_chaos=chaos.FaultBurstInjector(n=2, k=1),
                       **cfg)
        assert _outs(hs) == want
        assert eng.is_healthy()

    def test_rebuild_refreshes_brownout_and_reseeds_prefix(self,
                                                           rope_net):
        """Pre-fault page pressure (rung 3: no prefix inserts) must not
        gate the rebuild's re-primes: the replacement pool starts
        fresh, so the rung is recomputed before re-admission and the
        prefix cache IS re-seeded by the shared leading blocks."""
        shared = [3, 1, 2, 0] * 2            # two full 4-token blocks
        prompts = [shared + [5], shared + [7, 8]]
        cfg = dict(prompts=prompts, paging=PagedKVConfig(page_size=4))
        _, base = _run(rope_net, **cfg)
        want = _outs(base)
        eng = GenerationEngine(rope_net, V, slots=2,
                               supervisor=EngineSupervisor(),
                               overload=OverloadConfig(),
                               paging=PagedKVConfig(page_size=4))
        hs = [eng.submit(p, steps=5, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(prompts)]
        eng.step()                           # seated, cache seeded
        pool = eng.page_pool
        pool.seize(pool.free_count())        # total pressure: rung 3
        eng.step()
        assert eng._brownout == 3
        eng._decode_chaos = chaos.FaultBurstInjector(k=1)
        eng.run_until_idle()                 # fault -> rebuild
        assert eng._brownout == 0            # fresh pool: recomputed
        assert len(eng.prefix_cache) > 0     # re-seeded, not skipped
        assert _outs(hs) == want

    def test_expired_survivor_fails_at_rebuild_not_readmitted(
            self, rope_net):
        """A survivor whose deadline passes during fault handling must
        not pay a re-prefill or inflate the recovered count: the
        rebuild fails it with InferenceTimeout (mirroring the
        queue-pop check) instead of re-admitting it for the next
        step's reap to kill one rebuild later."""
        sup = EngineSupervisor()
        eng = GenerationEngine(rope_net, V, slots=1, supervisor=sup)
        h = eng.submit(PROMPTS[0], steps=20, top_k=1, timeout=60.0)
        eng.step()                           # seated, mid-stream

        def expire_then_fault():
            eng._slots[0].deadline = time.monotonic() - 1.0
            return chaos.InjectedFault()
        eng._decode_chaos = chaos.FaultBurstInjector(
            k=1, exc=expire_then_fault)
        eng.run_until_idle()
        with pytest.raises(InferenceTimeout):
            h.result(timeout=2.0)
        assert eng.is_healthy()              # rebuild itself succeeded
        assert sup.rebuilds == 1 and sup.recovered_requests == 0

    def test_fault_mid_rebuild_strands_no_waiters(self, rope_net):
        """A fault raised from INSIDE the rebuild's re-admission (the
        supervised escalation path) must still give every survivor a
        terminal event: slots were cleared up front, so without the
        rebuild's own cleanup the escalation _break could no longer
        see the not-yet-readmitted survivors and their callers hung."""
        sup = EngineSupervisor()
        eng = GenerationEngine(rope_net, V, slots=2, supervisor=sup,
                               decode_chaos=chaos.FaultBurstInjector(
                                   n=1, k=1))
        hs = [eng.submit(p, steps=6, top_k=1) for p in PROMPTS[:2]]
        orig_admit = eng._admit_one
        state = {"readmits": 0}

        def flaky_admit(req, slot, readmit=False):
            if readmit:
                state["readmits"] += 1
                if state["readmits"] == 2:   # second survivor's seat
                    raise RuntimeError("device died mid-rebuild")
            return orig_admit(req, slot, readmit=readmit)
        eng._admit_one = flaky_admit
        eng.run_until_idle()
        assert all(h.done for h in hs), "a survivor was stranded"
        assert not eng.is_healthy()
        assert sup.rebuilds == 0 and sup.escalations == 1

    def test_overload_controller_binds_one_engine(self, rope_net):
        """A pre-built OverloadController carries one engine's SLO
        evidence; wiring it into a second engine must raise, not
        silently cross-contaminate shedding decisions."""
        ctl = OverloadController(OverloadConfig())
        GenerationEngine(rope_net, V, slots=1, overload=ctl)
        with pytest.raises(ValueError, match="one OverloadController"):
            GenerationEngine(rope_net, V, slots=1, overload=ctl)

    def test_multi_fault_burst_within_budget(self, rope_net):
        """K consecutive faults with budget >= K: every fault costs one
        rebuild, every request still completes identically."""
        _, base = _run(rope_net, steps=7)
        want = _outs(base)
        sup = EngineSupervisor(budget=RestartBudget(3, 60.0))
        eng, hs = _run(rope_net, steps=7, supervisor=sup,
                       decode_chaos=chaos.FaultBurstInjector(n=1, k=3))
        assert _outs(hs) == want
        assert sup.rebuilds == 3 and eng.is_healthy()

    def test_rebuild_telemetry_and_health(self, rope_net):
        reg = MetricsRegistry()
        sup = EngineSupervisor()
        eng, hs = _run(rope_net, registry=reg, name="engine:sup",
                       supervisor=sup,
                       decode_chaos=chaos.FaultBurstInjector(n=2, k=1))
        assert all(h.done for h in hs)
        snap = reg.snapshot_compact()
        assert snap[SERVING_ENGINE_REBUILDS
                    + "{cause=decode_fault,model=engine:sup}"] == 1
        assert snap[SERVING_RECOVERED_REQUESTS
                    + "{model=engine:sup}"] >= 1
        h = eng.health()
        assert h["supervisor"]["rebuilds"] == 1
        assert h["supervisor"]["last_cause"] == "decode_fault"


class TestEscalation:
    def test_budget_exhaustion_escalates_to_fail_all(self, rope_net):
        """More faults than budget: the supervisor escalates to the
        PR 5 terminal state — every in-flight handle fails with the
        original error, health flips, submits are refused."""
        reg = MetricsRegistry()
        sup = EngineSupervisor(budget=RestartBudget(2, 60.0))
        eng, hs = _run(rope_net, supervisor=sup, registry=reg,
                       name="engine:esc",
                       decode_chaos=chaos.FaultBurstInjector(n=1, k=10))
        assert not eng.is_healthy()
        assert sup.escalations == 1
        snap = reg.snapshot_compact()
        assert snap[SERVING_ENGINE_ESCALATIONS
                    + "{model=engine:esc}"] == 1
        # escalations are NOT rebuilds: the rebuild counter counts only
        # the 2 budgeted rebuilds that actually happened
        assert snap[SERVING_ENGINE_REBUILDS
                    + "{cause=decode_fault,model=engine:esc}"] == 2
        for h in hs:
            assert h.done
            with pytest.raises(chaos.InjectedFault):
                h.result(timeout=0)
        with pytest.raises(EngineShutdown):
            eng.submit([1, 2], steps=2)

    def test_zero_budget_means_legacy_fail_all(self, rope_net):
        """RestartBudget(0): supervision configured but disabled — the
        first fault is terminal, exactly the unsupervised behavior."""
        sup = EngineSupervisor(budget=RestartBudget(0, 60.0))
        eng, _ = _run(rope_net, supervisor=sup,
                      decode_chaos=chaos.FaultBurstInjector(n=1, k=1))
        assert not eng.is_healthy() and sup.rebuilds == 0

    def test_window_expiry_restores_budget(self):
        t = [0.0]
        b = RestartBudget(2, 10.0, clock=lambda: t[0])
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()
        t[0] = 10.5                       # the window slides past both
        assert b.remaining() == 2
        assert b.try_acquire()

    def test_remaining_never_mutates(self):
        """remaining() is read from lock-free health/metrics probes
        racing the step thread's try_acquire — it must count without
        reassigning the ledger (a probe-time prune could drop a
        just-recorded restart and leak the crash-loop bound)."""
        t = [0.0]
        b = RestartBudget(2, 10.0, clock=lambda: t[0])
        assert b.try_acquire()
        ledger = b._acquired
        t[0] = 10.5                       # entry aged out of the window
        assert b.remaining() == 2
        assert b._acquired is ledger and ledger == [0.0]


# ---------------------------------------------------------------------
# satellite: the pop-to-seat handoff window
# ---------------------------------------------------------------------
class TestSeatWindow:
    def test_seat_fault_fails_terminally_without_supervisor(self,
                                                            rope_net):
        """A request popped from the queue but not yet seated must get
        a terminal event when the engine breaks in that window — before
        the fix it was stranded in neither the slot scan nor the queue
        drain and its caller hung forever."""
        eng = GenerationEngine(rope_net, V, slots=1,
                               seat_chaos=chaos.RaiseOnBatch(None, n=1))
        h0 = eng.submit(PROMPTS[0], steps=4, top_k=1)
        h1 = eng.submit(PROMPTS[1], steps=4, top_k=1)
        eng.run_until_idle()
        with pytest.raises(chaos.InjectedFault):
            h1.result(timeout=2.0)       # bounded: a hang fails loudly
        assert not eng.is_healthy()
        assert h0.done

    def test_seat_fault_recovers_with_supervisor(self, rope_net):
        _, base = _run(rope_net)
        want = _outs(base)
        sup = EngineSupervisor()
        eng, hs = _run(rope_net, supervisor=sup, n_slots=1,
                       seat_chaos=chaos.RaiseOnBatch(None, n=1))
        assert _outs(hs) == want
        assert eng.is_healthy()
        assert sup.last_cause == "admission_fault"

    def test_cancelled_seating_request_not_readmitted(self, rope_net):
        """A request cancelled inside the pop-to-seat window must not
        be re-admitted by the rebuild — no prefill dispatch for a dead
        stream, not counted recovered; it resolves RequestCancelled."""
        def cancel_then_fault(r):
            r.handle.cancel()
            return True
        sup = EngineSupervisor()
        eng = GenerationEngine(
            rope_net, V, slots=1, supervisor=sup,
            seat_chaos=chaos.RequestFaultInjector(match=cancel_then_fault))
        h = eng.submit(PROMPTS[0], steps=4, top_k=1)
        eng.run_until_idle()
        with pytest.raises(RequestCancelled):
            h.result(timeout=2.0)
        assert eng.is_healthy()           # rebuild succeeded regardless
        assert sup.rebuilds == 1 and sup.recovered_requests == 0

    def test_request_targeted_fault_selection(self, rope_net):
        """RequestFaultInjector picks its victim by request content, not
        admission index — robust to admission-order shifts."""
        _, base = _run(rope_net)
        want = _outs(base)
        inj = chaos.RequestFaultInjector(
            match=lambda r: r.prompt == PROMPTS[1])
        eng, hs = _run(rope_net, prefill_chaos=inj)
        with pytest.raises(chaos.InjectedFault):
            hs[1].result(timeout=0)
        assert hs[0].result(timeout=0) == want[0]
        assert hs[2].result(timeout=0) == want[2]
        assert eng.is_healthy()          # prefill domain: victim only


# ---------------------------------------------------------------------
# overload control
# ---------------------------------------------------------------------
class TestEarlyRejection:
    def test_injected_eta_rejects_deterministically(self, rope_net):
        """deadline < now + eta is refused AT SUBMIT with the typed
        error; the same request without a deadline (or with slack) is
        admitted — pinned with an injected estimator so the decision is
        a pure function of its inputs."""
        ov = OverloadConfig(queue_eta=lambda e, r, now: 10.0)
        eng = GenerationEngine(rope_net, V, slots=1, overload=ov)
        with pytest.raises(ServingOverloaded):
            eng.submit([1, 2], steps=2, top_k=1, timeout=1.0)
        h = eng.submit([1, 2], steps=2, top_k=1, timeout=60.0)
        h2 = eng.submit([3, 4], steps=2, top_k=1)      # no deadline
        eng.run_until_idle()
        assert h.result(timeout=0) and h2.result(timeout=0)
        assert eng.health()["overload"]["early_rejected_total"] == 1

    def test_no_rejection_before_rate_calibrates(self, rope_net):
        """The default estimator never rejects on ignorance: with no
        observed admissions there is no rate, so a tight deadline is
        admitted (and reaped by the normal deadline machinery)."""
        ov = OverloadConfig(min_samples=2)
        eng = GenerationEngine(rope_net, V, slots=1, overload=ov)
        h = eng.submit([1, 2], steps=2, top_k=1, timeout=30.0)
        eng.run_until_idle()
        assert h.result(timeout=0)


class TestShedding:
    def test_sustained_breach_sheds_lowest_priority_first(self,
                                                          rope_net):
        """Breach evidence in the window + queue beyond servable depth:
        the lowest-priority youngest queued work sheds with
        ServingOverloaded; higher classes survive and complete."""
        ov = OverloadConfig(ttft_slo_s=0.001, min_samples=2,
                            breach_window=4, shed_to_depth=2)
        eng = GenerationEngine(rope_net, V, slots=1, overload=ov,
                               queue_limit=16)
        for _ in range(4):                 # deterministic breach
            eng._overload.observe_ttft(1.0, time.monotonic())
        hi = eng.submit([1, 2], steps=4, top_k=1, priority=5)
        mid = eng.submit([3, 4], steps=4, top_k=1, priority=1)
        lo1 = eng.submit([5, 6], steps=4, top_k=1, priority=0)
        lo2 = eng.submit([7, 8], steps=4, top_k=1, priority=0)
        eng.step()       # shed (depth 4 -> 2: both lows), then admit hi
        for h in (lo1, lo2):
            with pytest.raises(ServingOverloaded):
                h.result(timeout=2.0)
        eng.run_until_idle()
        assert hi.result(timeout=0) and mid.result(timeout=0)
        assert eng._overload.shed_total == 2

    def test_no_shedding_without_breach(self, rope_net):
        ov = OverloadConfig(ttft_slo_s=1000.0, min_samples=1,
                            shed_to_depth=1)
        eng, hs = _run(rope_net, prompts=PROMPTS, n_slots=1,
                       overload=ov, queue_limit=16)
        assert all(h.error is None for h in hs)
        assert eng._overload.shed_total == 0

    def test_shed_resets_breach_window(self, rope_net):
        """One burst of slow admissions must not bleed the queue dry
        forever: a shed round clears the evidence window, so the next
        round needs fresh post-shed samples."""
        ov = OverloadConfig(ttft_slo_s=0.001, min_samples=2,
                            breach_window=4, shed_to_depth=0)
        eng = GenerationEngine(rope_net, V, slots=1, overload=ov,
                               queue_limit=16)
        ctl = eng._overload
        for _ in range(4):
            ctl.observe_ttft(1.0, time.monotonic())
        eng.submit([1, 2], steps=2, top_k=1)
        victims = ctl.shed(eng)
        assert len(victims) == 1
        eng.submit([3, 4], steps=2, top_k=1)
        assert ctl.shed(eng) == []        # window cleared: no evidence


class TestBrownout:
    def _spec_engine(self, rope_net, fracs=(0.5, 0.3, 0.1)):
        return GenerationEngine(
            rope_net, V, slots=2,
            overload=OverloadConfig(brownout_enter_fracs=fracs),
            paging=PagedKVConfig(page_size=4),
            speculation=SpeculationConfig(
                draft=prompt_lookup_proposer(2), gamma=2))

    def test_ladder_escalates_and_restores(self, rope_net):
        """Free-page pressure walks the ladder up (gamma drop -> spec
        off -> no prefix inserts) and back down when pressure clears —
        feature degradation, never availability loss: the active
        request completes either way."""
        eng = self._spec_engine(rope_net)
        pool = eng.page_pool
        h = eng.submit([1, 2, 3], steps=10, top_k=1)
        eng.step()
        assert eng._brownout == 0
        pool.seize(pool.free_count() - int(0.35 * pool.usable))
        eng.step()
        assert eng._brownout == 1         # reduced gamma
        pool.seize(pool.free_count() - int(0.05 * pool.usable))
        eng.step()
        assert eng._brownout == 3         # spec off + no prefix inserts
        pool.restore()
        eng.step()
        assert eng._brownout == 0         # pressure cleared: restored
        eng.run_until_idle()
        assert h.result(timeout=0)

    def test_hysteresis_holds_level_near_threshold(self, rope_net):
        eng = self._spec_engine(rope_net)
        pool = eng.page_pool
        ctl = eng._overload
        pool.seize(pool.free_count() - int(0.45 * pool.usable))
        assert ctl.brownout_level(eng) == 1
        # restore to just above the enter threshold but inside the
        # hysteresis margin: the rung must HOLD
        pool.restore()
        pool.seize(pool.free_count() - int(0.55 * pool.usable))
        assert ctl.brownout_level(eng) == 1
        pool.restore()                     # fully clear
        assert ctl.brownout_level(eng) == 0

    def test_release_reachable_when_margin_overflows_one(self, rope_net):
        """enter_frac + clear_margin > 1.0 must not latch the rung
        forever: the release point caps at 1.0, so a fully free pool
        always restores."""
        eng = self._spec_engine(rope_net, fracs=(0.95, 0.5, 0.1))
        pool = eng.page_pool
        ctl = eng._overload
        pool.seize(pool.free_count() - int(0.9 * pool.usable))
        assert ctl.brownout_level(eng) == 1
        pool.restore()                     # free_frac == 1.0 exactly
        assert ctl.brownout_level(eng) == 0

    def test_negative_clear_margin_rejected(self):
        with pytest.raises(ValueError, match="brownout_clear_margin"):
            OverloadConfig(brownout_clear_margin=-0.1)

    def test_brownout_stops_prefix_inserts(self, rope_net):
        eng = GenerationEngine(
            rope_net, V, slots=1,
            overload=OverloadConfig(brownout_enter_fracs=(0.9, 0.85,
                                                          0.8)),
            paging=PagedKVConfig(page_size=2))
        pool = eng.page_pool
        pool.seize(int(pool.free_count() - 0.5 * pool.usable))
        h = eng.submit([1, 2, 3, 4, 5], steps=2, top_k=1)
        eng.run_until_idle()
        assert h.result(timeout=0)
        assert len(eng.prefix_cache) == 0  # rung 3: inserts off
        pool.restore()
        h2 = eng.submit([1, 2, 3, 4, 5], steps=2, top_k=1)
        eng.run_until_idle()
        h2.result(timeout=0)
        assert len(eng.prefix_cache) > 0   # restored: inserts resume

    def test_greedy_outputs_unchanged_under_brownout(self, rope_net):
        """Brownout degrades throughput levers only: greedy outputs are
        the argmax chain with or without speculation, so a mid-stream
        rung change never changes tokens."""
        cfg = dict(paging=PagedKVConfig(page_size=4),
                   speculation=SpeculationConfig(
                       draft=prompt_lookup_proposer(2), gamma=2))
        _, base = _run(rope_net, **cfg)
        want = _outs(base)
        eng = GenerationEngine(
            rope_net, V, slots=2,
            overload=OverloadConfig(brownout_enter_fracs=(0.99, 0.98,
                                                          0.97)),
            **cfg)
        # pool almost exhausted from the start: permanent deep brownout
        hs = [eng.submit(p, steps=5, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:3])]
        eng.run_until_idle()
        assert _outs(hs) == want


# ---------------------------------------------------------------------
# draining
# ---------------------------------------------------------------------
class TestDrain:
    def test_drain_finishes_actives_fails_queued(self, rope_net):
        eng = GenerationEngine(rope_net, V, slots=1)
        act = eng.submit(PROMPTS[0], steps=6, top_k=1,
                         rng=np.random.default_rng(0))
        queued = eng.submit(PROMPTS[1], steps=6, top_k=1)
        eng.step()                        # seat the first
        assert eng.drain(timeout=60.0)
        assert act.done and act.error is None
        assert len(act.generated) == 6    # ran to natural retirement
        with pytest.raises(EngineShutdown):
            queued.result(timeout=0)
        with pytest.raises(EngineShutdown):
            eng.submit([1], steps=1)
        assert not eng.is_ready()
        assert eng.health()["draining"] is True
        assert eng.active_slots() == 0    # the clean handoff point

    def test_drain_under_background_loop(self, rope_net):
        eng = GenerationEngine(rope_net, V, slots=2).start()
        try:
            hs = [eng.submit(p, steps=5, top_k=1,
                             rng=np.random.default_rng(i))
                  for i, p in enumerate(PROMPTS[:2])]
            t0 = time.monotonic()
            while eng.active_slots() < 2 and not all(h.done
                                                     for h in hs):
                assert time.monotonic() - t0 < 60, "never admitted"
                time.sleep(0.005)        # drain fails QUEUED work; the
            assert eng.drain(timeout=60.0)  # test wants actives finish
            for h in hs:
                assert h.result(timeout=0)
        finally:
            eng.shutdown()

    def test_drain_timeout_reports_false(self, rope_net):
        eng = GenerationEngine(rope_net, V, slots=1)
        eng.submit([1, 2], steps=500, top_k=1, max_length=None)
        eng.step()
        assert eng.drain(timeout=0.0) is False   # active still seated
        assert eng.active_slots() == 1

    def test_draining_gauge(self, rope_net):
        reg = MetricsRegistry()
        eng = GenerationEngine(rope_net, V, slots=1, registry=reg,
                               name="engine:drain")
        key = SERVING_DRAINING + "{model=engine:drain}"
        assert reg.snapshot_compact()[key] == 0.0
        eng.drain(timeout=1.0)
        assert reg.snapshot_compact()[key] == 1.0


# ---------------------------------------------------------------------
# satellite: AdmissionQueue close-drain + shed primitives
# ---------------------------------------------------------------------
class TestAdmissionQueueCloseDrain:
    def test_concurrent_blocked_submitters_all_get_terminal_error(self):
        """Blocked `submit` callers on a full queue: close() must wake
        every one with EngineShutdown — none may hang, none may slip
        into a closed queue."""
        q = AdmissionQueue(limit=1, policy="block")
        q.submit(GenerationRequest([1], 1))       # fill the bound
        results = []
        n = 6

        def blocked_submit(i):
            try:
                q.submit(GenerationRequest([i], 1))
                results.append(("in", i))
            except EngineShutdown:
                results.append(("shutdown", i))
            except Exception as e:  # noqa: BLE001 — recorded for assert
                results.append((type(e).__name__, i))

        ts = [threading.Thread(target=blocked_submit, args=(i,))
              for i in range(n)]
        for t in ts:
            t.start()
        time.sleep(0.15)                  # let them all park
        drained = q.close()
        for t in ts:
            t.join(timeout=5.0)
        assert not any(t.is_alive() for t in ts), "a submitter hung"
        assert len(drained) == 1
        assert sorted(r[0] for r in results) == ["shutdown"] * n

    def test_shed_lowest_victim_order(self):
        q = AdmissionQueue(limit=16)
        rs = [GenerationRequest([i], 1, priority=p)
              for i, p in enumerate([2, 0, 0, 1, 0])]
        for r in rs:
            q.submit(r)
        victims = q.shed_lowest(keep=2)
        # lowest class (0) youngest-first: seq 4, 2, then the third
        # shed comes from class 1
        assert victims == [rs[4], rs[2], rs[3]] or \
            victims == [rs[4], rs[2], rs[1]]
        assert len(victims) == 3 and q.depth() == 2
        assert q.shed_lowest(keep=5) == []

    def test_depth_ahead_counts_peers_and_better(self):
        q = AdmissionQueue(limit=16)
        for p in (0, 1, 1, 3):
            q.submit(GenerationRequest([1], 1, priority=p))
        assert q.depth_ahead(2) == 1      # only the 3
        assert q.depth_ahead(1) == 3      # both 1s (peers) + the 3
        assert q.depth_ahead(0) == 4


# ---------------------------------------------------------------------
# chaos injector units
# ---------------------------------------------------------------------
class TestInjectors:
    def test_fault_burst_fires_k_then_clears(self):
        inj = chaos.FaultBurstInjector(n=2, k=3)
        chaos.fire(inj, 0)
        chaos.fire(inj, 1)                # below n: clean
        for i in range(3):
            with pytest.raises(chaos.InjectedFault):
                chaos.fire(inj, 2)        # same index re-presented
        chaos.fire(inj, 2)                # burst spent: clean forever
        chaos.fire(inj, 7)
        assert inj.faults_fired == 3

    def test_fault_burst_window_bounds_indices(self):
        inj = chaos.FaultBurstInjector(n=2, k=5, window=2)
        with pytest.raises(chaos.InjectedFault):
            chaos.fire(inj, 2)
        with pytest.raises(chaos.InjectedFault):
            chaos.fire(inj, 3)
        chaos.fire(inj, 4)                # outside [2, 4): clean
        assert inj.faults_fired == 2

    def test_request_targeted_once_latch(self):
        inj = chaos.RequestFaultInjector(match=lambda r: r == "victim")
        chaos.fire(inj, 0, ctx="bystander")
        with pytest.raises(chaos.InjectedFault):
            chaos.fire(inj, 1, ctx="victim")
        chaos.fire(inj, 2, ctx="victim")  # once=True: latched
        chaos.fire(inj, 3, ctx=None)      # indexed seams: no-op


# ---------------------------------------------------------------------
# acceptance: zero retraces after recovery (post full-envelope warmup)
# ---------------------------------------------------------------------
def _compile_total():
    c = monitoring.global_registry().get(runtime.COMPILE_COUNTER)
    return 0.0 if c is None else c.total()


class TestNoRetraceAfterRecovery:
    def test_recovery_compiles_nothing_new(self):
        """After a full-envelope warmup(), a mid-stream fault + arena
        rebuild + survivor re-prime + continued decode hits only warm
        shapes: re-primes land in the warmed prefill buckets, the arena
        skeleton/scatter/decode reuse their compiled signatures
        (recompile-watcher-pinned, the PR 3 bar applied to recovery)."""
        monitoring.ensure_started()
        net = TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                        n_heads=2, n_layers=2,
                                        max_length=32,
                                        positional="rope").init()
        sup = EngineSupervisor()
        eng = GenerationEngine(net, V, slots=2, supervisor=sup)
        eng.warmup()          # default: every bucket up to capacity
        warm = _compile_total()
        # armed AFTER warmup: the fault must land mid-traffic, past the
        # compile-count snapshot (warmup consumes dispatch indices too)
        eng._decode_chaos = chaos.FaultBurstInjector(
            n=eng._dispatches + 3, k=1)
        hs = [eng.submit(p, steps=6, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate(PROMPTS[:3])]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        assert sup.rebuilds == 1
        assert _compile_total() == warm, (
            "recovery retraced after warmup — the rebuild must reuse "
            "the warm prefill buckets and arena dispatch shapes")

    def test_paged_recovery_compiles_nothing_new(self):
        """Same bar for the paged arena with the prefix cache on: the
        rebuilt pool/page-store/prefix plumbing reuses the compiled
        gather/scatter signatures, and post-rebuild re-primes (fresh
        AND prefix-hit suffix buckets) stay inside the warmed set."""
        monitoring.ensure_started()
        net = TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                        n_heads=2, n_layers=2,
                                        max_length=32,
                                        positional="rope").init()
        sup = EngineSupervisor()
        eng = GenerationEngine(
            net, V, slots=2, supervisor=sup,
            paging=PagedKVConfig(page_size=4))
        eng.warmup()
        warm = _compile_total()
        eng._decode_chaos = chaos.FaultBurstInjector(
            n=eng._dispatches + 3, k=1)
        shared = [3, 1, 2, 0] * 2           # two cached full blocks
        hs = [eng.submit(p, steps=6, top_k=1,
                         rng=np.random.default_rng(i))
              for i, p in enumerate([shared + [5], shared + [7, 8],
                                     [9, 9]])]
        eng.run_until_idle()
        assert all(h.done for h in hs)
        assert sup.rebuilds == 1
        assert _compile_total() == warm, (
            "paged recovery retraced after warmup")
