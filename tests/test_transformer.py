"""Transformer stack tests: LayerNormalization + SelfAttentionLayer confs
and the TextGenerationTransformer zoo model (post-parity long-context
counterpart of TextGenerationLSTM)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    LayerNormalization, RnnOutputLayer, SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.zoo import TextGenerationTransformer

RNG = np.random.default_rng(0)


class TestLayerNormalization:
    def test_normalizes_features(self):
        ln = LayerNormalization()
        p, _ = ln.init(jax.random.PRNGKey(0), InputType.feed_forward(16))
        x = jnp.asarray(RNG.standard_normal((8, 16)) * 5 + 3, jnp.float32)
        y, _ = ln.apply(p, x, {})
        np.testing.assert_allclose(np.asarray(y).mean(1), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y).std(1), 1.0, atol=1e-3)

    def test_rnn_format_per_timestep(self):
        ln = LayerNormalization()
        p, _ = ln.init(jax.random.PRNGKey(0), InputType.recurrent(8, 5))
        x = jnp.asarray(RNG.standard_normal((3, 8, 5)), jnp.float32)
        y, _ = ln.apply(p, x, {})
        np.testing.assert_allclose(np.asarray(y).mean(axis=1), 0.0,
                                   atol=1e-5)

    def test_gradient_check(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.util.gradient_check import check_gradients
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(0.01)).list()
                .layer(LayerNormalization())
                .layer(RnnOutputLayer(n_out=3, loss="mcxent",
                                      activation="softmax"))
                .set_input_type(InputType.recurrent(4, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.standard_normal((2, 4, 6)).astype(np.float32)
        y = np.zeros((2, 3, 6), np.float32)
        y[:, 0, :] = 1.0
        assert check_gradients(net, DataSet(x, y))


class TestSelfAttentionLayer:
    def test_matches_mha_block(self):
        """Layer output == parallel.sequence.MultiHeadSelfAttention with
        the same weights (the layer is the conf-DSL face of that block)."""
        from deeplearning4j_tpu.parallel.sequence import (
            MultiHeadSelfAttention,
        )
        F, H, T = 16, 4, 10
        layer = SelfAttentionLayer(n_out=F, n_heads=H, causal=True,
                                   activation="identity")
        p, _ = layer.init(jax.random.PRNGKey(3), InputType.recurrent(F, T))
        x = jnp.asarray(RNG.standard_normal((2, F, T)), jnp.float32)
        y, _ = layer.apply(p, x, {})

        mha = MultiHeadSelfAttention(F, H, impl="blockwise", causal=True)
        mp = {"wq": p["Wq"], "wk": p["Wk"], "wv": p["Wv"], "wo": p["Wo"]}
        ref = mha.apply(mp, jnp.transpose(x, (0, 2, 1)))  # [B,T,E]
        ref = jnp.transpose(ref, (0, 2, 1)) + p["bo"][None, :, None]
        # layer adds biases on q/k/v too (zeros at init) and on o
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   atol=1e-5)

    def test_causality(self):
        """Changing a future timestep must not affect earlier outputs."""
        F, T = 8, 12
        layer = SelfAttentionLayer(n_out=F, n_heads=2, causal=True,
                                   activation="identity")
        p, _ = layer.init(jax.random.PRNGKey(1), InputType.recurrent(F, T))
        x = jnp.asarray(RNG.standard_normal((1, F, T)), jnp.float32)
        y1, _ = layer.apply(p, x, {})
        x2 = x.at[:, :, -1].set(99.0)
        y2, _ = layer.apply(p, x2, {})
        np.testing.assert_allclose(np.asarray(y1)[:, :, :-1],
                                   np.asarray(y2)[:, :, :-1], atol=1e-5)

    def test_heads_divisibility_validated(self):
        layer = SelfAttentionLayer(n_out=10, n_heads=4)
        with pytest.raises(ValueError):
            layer.init(jax.random.PRNGKey(0), InputType.recurrent(10, 4))


class TestPositionalEmbedding:
    def test_adds_position_signal(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            PositionalEmbeddingLayer,
        )
        layer = PositionalEmbeddingLayer(max_length=16)
        p, _ = layer.init(jax.random.PRNGKey(0), InputType.recurrent(4, 8))
        x = jnp.zeros((2, 4, 8), jnp.float32)
        y, _ = layer.apply(p, x, {})
        # identical inputs at different positions now differ
        assert not np.allclose(np.asarray(y)[:, :, 0],
                               np.asarray(y)[:, :, 1])
        with pytest.raises(ValueError):
            layer.apply(p, jnp.zeros((1, 4, 20), jnp.float32), {})


class TestBlockwiseKeyMask:
    def test_key_mask_matches_truncation(self):
        """Masked trailing keys == attention over the truncated sequence
        (for the valid query positions)."""
        from deeplearning4j_tpu.parallel.sequence import (
            blockwise_attention,
        )
        B, H, T, D, TV = 2, 2, 12, 8, 9  # TV = valid length
        q = jnp.asarray(RNG.standard_normal((B, H, T, D)), jnp.float32)
        k = jnp.asarray(RNG.standard_normal((B, H, T, D)), jnp.float32)
        v = jnp.asarray(RNG.standard_normal((B, H, T, D)), jnp.float32)
        km = jnp.asarray(np.arange(T)[None, :] < TV).repeat(B, 0)
        out = blockwise_attention(q, k, v, causal=False, block_size=5,
                                  key_mask=km)
        ref = blockwise_attention(q[:, :, :TV], k[:, :, :TV], v[:, :, :TV],
                                  causal=False, block_size=5)
        np.testing.assert_allclose(np.asarray(out)[:, :, :TV],
                                   np.asarray(ref), atol=1e-5)


class TestTextGenerationTransformer:
    def test_learns_copy_task(self):
        """Tiny LM learns 'next token = current token' far above chance."""
        V, T, B = 12, 16, 32
        model = TextGenerationTransformer(
            vocab_size=V, embed_dim=32, n_heads=4, n_layers=2,
            max_length=T, updater=Adam(3e-3), seed=5)
        net = model.init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, (B, T))
        x = np.zeros((B, V, T), np.float32)
        x[np.arange(B)[:, None], ids, np.arange(T)[None, :]] = 1.0
        y = np.roll(x, -1, axis=2)  # predict the next token
        y[:, :, -1] = x[:, :, -1]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        losses = []
        for _ in range(60):
            net._fit_batch(DataSet({"in": x}, {"out": y}))
            losses.append(net.score_value)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        out = net.output(x)
        out = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
        # exclude the final position (wraps); accuracy >> 1/V chance
        pred = out[:, :, :-1].argmax(1)
        target = ids[:, 1:]
        acc = float((pred == target).mean())
        assert acc > 0.5, acc

    def test_sampling_runs(self):
        V = 12
        model = TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=8)
        net = model.init()
        ids = model.sample(net, [1, 2], steps=5)
        assert len(ids) == 7 and all(0 <= i < V for i in ids)


class TestTransformerSerde:
    def test_config_json_roundtrip(self):
        """New layer confs (LN / attention / positional embedding) survive
        the config JSON round trip with their fields intact."""
        from deeplearning4j_tpu.nn.conf.network import (
            ComputationGraphConfiguration,
        )
        conf = TextGenerationTransformer(
            vocab_size=16, embed_dim=16, n_heads=2, n_layers=1,
            max_length=8).conf()
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert {k: type(v).__name__ for k, v in conf.vertices.items()} == \
            {k: type(v).__name__ for k, v in conf2.vertices.items()}
        at = conf2.vertices["attn0"].layer
        assert (at.n_heads, at.causal, at.block_size) == (2, True, 512)
        assert conf2.vertices["pos"].layer.max_length == 8
        assert conf2.vertices["ln0a"].layer.eps == 1e-5

    def test_checkpoint_roundtrip(self):
        """write_model/restore on the transformer: identical outputs."""
        import os
        import tempfile
        from deeplearning4j_tpu.util.model_serializer import (
            restore_computation_graph, write_model,
        )
        model = TextGenerationTransformer(vocab_size=10, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=6)
        net = model.init()
        x = np.zeros((2, 10, 6), np.float32)
        ids = RNG.integers(0, 10, (2, 6))
        x[np.arange(2)[:, None], ids, np.arange(6)[None, :]] = 1.0
        before = np.asarray(net.output(x)[0] if isinstance(net.output(x),
                                                           (list, tuple))
                            else net.output(x))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "t.zip")
            write_model(net, p)
            net2 = restore_computation_graph(p)
        out2 = net2.output(x)
        after = np.asarray(out2[0] if isinstance(out2, (list, tuple))
                           else out2)
        np.testing.assert_allclose(before, after, atol=1e-6)


class TestStreamingDecode:
    """KV-cache incremental decoding (rnn_time_step) == full forward.

    The attention-era analog of the reference's rnnTimeStep streaming
    equivalence (MultiLayerNetwork.rnnTimeStep: streamed outputs match the
    full-sequence forward at every position)."""

    def _net(self):
        model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                          n_heads=2, n_layers=2,
                                          max_length=16)
        return model, model.init()

    def test_streaming_matches_full_forward(self):
        model, net = self._net()
        V, T = 12, 10
        ids = RNG.integers(0, V, T)
        x = np.zeros((1, V, T), np.float32)
        x[0, ids, np.arange(T)] = 1.0
        out = net.output(x)
        full = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)

        def one_hot(seq):
            h = np.zeros((1, V, len(seq)), np.float32)
            h[0, seq, np.arange(len(seq))] = 1.0
            return h

        # prime with the first 4 tokens, then stream one at a time
        net.rnn_clear_previous_state()
        got = np.asarray(net.rnn_time_step(one_hot(ids[:4])))
        np.testing.assert_allclose(got[0], full[0, :, :4], atol=1e-4)
        for t in range(4, T):
            got = np.asarray(net.rnn_time_step(one_hot(ids[t:t + 1])))
            np.testing.assert_allclose(got[0, :, 0], full[0, :, t],
                                       atol=1e-4,
                                       err_msg=f"position {t}")

    def test_clear_state_resets(self):
        model, net = self._net()
        V = 12
        x = np.zeros((1, V, 3), np.float32)
        x[0, [1, 2, 3], np.arange(3)] = 1.0
        a = np.asarray(net.rnn_time_step(x))
        net.rnn_clear_previous_state()
        b = np.asarray(net.rnn_time_step(x))
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_sample_stream_runs(self):
        model, net = self._net()
        ids = model.sample_stream(net, [1, 2, 3], steps=5)
        assert len(ids) == 8
        assert all(0 <= i < 12 for i in ids)

    def test_streaming_state_stripped_from_training(self):
        """A training step after streaming must not see the KV cache."""
        model, net = self._net()
        V = 12
        x = np.zeros((1, V, 3), np.float32)
        x[0, [1, 2, 3], np.arange(3)] = 1.0
        net.rnn_time_step(x)
        assert any("kv_k" in s for s in net.state.values()
                   if isinstance(s, dict))
        y = np.roll(x, -1, axis=2)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.fit(DataSet(x, y))           # must not raise / use the cache
        net.rnn_clear_previous_state()
        assert not any("kv_k" in s for s in net.state.values()
                       if isinstance(s, dict))

    def test_stream_budget_guard(self):
        """Streaming past cache_length must raise host-side (the device
        dynamic_update_slice would silently clamp)."""
        import pytest
        model = TextGenerationTransformer(vocab_size=8, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=4)
        net = model.init()
        x = np.zeros((1, 8, 2), np.float32)
        x[0, [1, 2], np.arange(2)] = 1.0
        net.rnn_time_step(x)
        net.rnn_time_step(x)                      # exactly at capacity
        with pytest.raises(ValueError, match="streaming capacity"):
            net.rnn_time_step(x)
        net.rnn_clear_previous_state()
        net.rnn_time_step(x)                      # counter reset

    def test_tbptt_with_attention_trains(self):
        """carry_rnn (tbptt) must NOT enter the streaming decode path:
        a MultiLayerNetwork with attention + tbptt trains full-context
        per chunk (cache_length unset)."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            RnnOutputLayer, SelfAttentionLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet
        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                          causal=True))
                .layer(RnnOutputLayer(n_in=8, n_out=3, loss="mcxent",
                                      activation="softmax"))
                .set_input_type(InputType.recurrent(8, 12))
                .tbptt(4, 4)
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.standard_normal((2, 8, 12)).astype(np.float32)
        y = np.zeros((2, 3, 12), np.float32)
        y[:, 0, :] = 1.0
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)


class TestGroupedQueryAttention:
    """n_kv_heads < n_heads: grouped-query attention — K/V params and the
    streaming cache shrink by n_heads/n_kv_heads."""

    def _layer(self, n_kv, cache=0):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        layer = SelfAttentionLayer(n_out=16, n_heads=4, n_kv_heads=n_kv,
                                   causal=True, activation="identity",
                                   cache_length=cache)
        p, s = layer.init(jax.random.PRNGKey(5), InputType.recurrent(16, 8))
        return layer, p, s

    def test_param_shapes_shrink(self):
        layer, p, _ = self._layer(2)
        assert p["Wq"].shape == (16, 16)
        assert p["Wk"].shape == (16, 8)     # 2 kv heads x d=4
        assert p["Wv"].shape == (16, 8)
        assert p["bk"].shape == (8,)

    def test_equals_mha_when_kv_heads_match(self):
        # n_kv_heads=n_heads must be numerically identical to the default
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        full = SelfAttentionLayer(n_out=16, n_heads=4, causal=True,
                                  activation="identity")
        gqa = SelfAttentionLayer(n_out=16, n_heads=4, n_kv_heads=4,
                                 causal=True, activation="identity")
        p1, _ = full.init(jax.random.PRNGKey(7), InputType.recurrent(16, 8))
        p2, _ = gqa.init(jax.random.PRNGKey(7), InputType.recurrent(16, 8))
        x = jnp.asarray(RNG.standard_normal((2, 16, 8)), jnp.float32)
        y1, _ = full.apply(p1, x, {})
        y2, _ = gqa.apply(p2, x, {})
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                                   atol=1e-6)

    def test_gqa_matches_explicit_repeat(self):
        # GQA == MHA run with the K/V heads explicitly repeated
        layer, p, _ = self._layer(2)
        x = jnp.asarray(RNG.standard_normal((2, 16, 8)), jnp.float32)
        y, _ = layer.apply(p, x, {})

        # build the equivalent full-head params by tiling Wk/Wv per group
        import numpy as onp
        d = 4
        wk = onp.asarray(p["Wk"]).reshape(16, 2, d)
        wv = onp.asarray(p["Wv"]).reshape(16, 2, d)
        wk_full = onp.repeat(wk, 2, axis=1).reshape(16, 16)
        wv_full = onp.repeat(wv, 2, axis=1).reshape(16, 16)
        bk = onp.repeat(onp.asarray(p["bk"]).reshape(2, d), 2, 0).reshape(-1)
        bv = onp.repeat(onp.asarray(p["bv"]).reshape(2, d), 2, 0).reshape(-1)
        full = SelfAttentionLayer(n_out=16, n_heads=4, causal=True,
                                  activation="identity")
        pf = {"Wq": p["Wq"], "bq": p["bq"], "Wo": p["Wo"], "bo": p["bo"],
              "Wk": jnp.asarray(wk_full), "bk": jnp.asarray(bk),
              "Wv": jnp.asarray(wv_full), "bv": jnp.asarray(bv)}
        yf, _ = full.apply(pf, x, {})
        np.testing.assert_allclose(np.asarray(y), np.asarray(yf),
                                   atol=1e-5)

    def test_streaming_cache_shrinks_and_matches_full(self):
        layer, p, _ = self._layer(2, cache=8)
        x = jnp.asarray(RNG.standard_normal((1, 16, 6)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        state = {}
        outs = []
        for t in range(6):
            y, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
            outs.append(np.asarray(y)[:, :, 0])
        assert state["kv_k"].shape == (1, 2, 8, 4)   # Hkv=2, not 4
        np.testing.assert_allclose(np.stack(outs, -1), np.asarray(full),
                                   atol=1e-4)

    def test_bad_divisibility_rejected(self):
        import pytest
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        layer = SelfAttentionLayer(n_out=16, n_heads=4, n_kv_heads=3)
        with pytest.raises(ValueError, match="n_kv_heads"):
            layer.init(jax.random.PRNGKey(0), InputType.recurrent(16, 8))

    def test_serde_round_trip(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            layer_from_dict, layer_to_dict,
        )
        layer = SelfAttentionLayer(n_out=16, n_heads=8, n_kv_heads=2,
                                   cache_length=64)
        back = layer_from_dict(layer_to_dict(layer))
        assert back.n_kv_heads == 2 and back.cache_length == 64

    def test_zero_and_negative_kv_heads_rejected(self):
        import pytest
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        for bad in (0, -2):
            layer = SelfAttentionLayer(n_out=16, n_heads=4, n_kv_heads=bad)
            with pytest.raises(ValueError, match="n_kv_heads"):
                layer.init(jax.random.PRNGKey(0),
                           InputType.recurrent(16, 8))

    def test_tensor_parallel_rejects_gqa_params(self):
        import pytest
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.parallel.mesh import make_mesh
        from deeplearning4j_tpu.parallel.tensor import shard_mha_params
        layer, p, _ = self._layer(2)
        mesh = make_mesh(shape=(8,), axis_names=("model",))
        with pytest.raises(ValueError, match="grouped-query"):
            shard_mha_params(p, mesh)


class TestRope:
    """Rotary position embeddings on SelfAttentionLayer."""

    def _layer(self, **kw):
        layer = SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                   activation="identity", rope=True, **kw)
        p, s = layer.init(jax.random.PRNGKey(3), InputType.recurrent(16, 8))
        return layer, p

    def test_rope_changes_output(self):
        layer, p = self._layer()
        plain = SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                   activation="identity")
        x = jnp.asarray(RNG.standard_normal((1, 16, 8)), jnp.float32)
        y_rope, _ = layer.apply(p, x, {})
        y_plain, _ = plain.apply(p, x, {})
        assert float(jnp.max(jnp.abs(y_rope - y_plain))) > 1e-3

    def test_rotation_preserves_norm(self):
        layer, p = self._layer()
        q = jnp.asarray(RNG.standard_normal((1, 2, 8, 8)), jnp.float32)
        rq = layer._rope(q, jnp.arange(8))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(q), axis=-1),
            np.linalg.norm(np.asarray(rq), axis=-1), rtol=1e-5)

    def test_scores_depend_on_relative_position_only(self):
        # the defining property: <rope(q, i), rope(k, j)> is a function of
        # (i - j), so shifting both positions leaves the score unchanged
        layer, p = self._layer()
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.standard_normal((1, 1, 1, 8)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 1, 1, 8)), jnp.float32)

        def score(i, j):
            qi = layer._rope(q, jnp.array([i]))
            kj = layer._rope(k, jnp.array([j]))
            return float(jnp.sum(qi * kj))

        assert abs(score(5, 2) - score(105, 102)) < 1e-3
        assert abs(score(5, 2) - score(5, 3)) > 1e-4  # but offset matters

    def test_streaming_matches_full(self):
        layer, p = self._layer(cache_length=8)
        x = jnp.asarray(RNG.standard_normal((1, 16, 6)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        state, outs = {}, []
        for t in range(6):
            y, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
            outs.append(np.asarray(y)[:, :, 0])
        np.testing.assert_allclose(np.stack(outs, -1), np.asarray(full),
                                   atol=1e-4)

    def test_odd_head_dim_rejected_at_init(self):
        layer = SelfAttentionLayer(n_out=6, n_heads=2, rope=True,
                                   activation="identity")
        with pytest.raises(ValueError, match="even head dim"):
            layer.init(jax.random.PRNGKey(0), InputType.recurrent(6, 4))

    def test_serde_round_trip(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            layer_from_dict, layer_to_dict,
        )
        layer = SelfAttentionLayer(n_out=16, rope=True, rope_base=5e5)
        back = layer_from_dict(layer_to_dict(layer))
        assert back.rope and back.rope_base == 5e5


class TestRopeTransformer:
    def test_rope_variant_trains_and_streams(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                          n_heads=2, n_layers=2,
                                          max_length=16,
                                          positional="rope", n_kv_heads=1)
        net = model.init()
        assert "pos" not in net.conf.vertices      # no position table
        V, T = 12, 10
        ids = RNG.integers(0, V, (1, T))
        x = np.zeros((1, V, T), np.float32)
        x[0, ids[0], np.arange(T)] = 1.0
        y = np.roll(x, -1, axis=2)
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)
        # streaming decode == full forward (rope absolute offsets correct)
        out = net.output(x)
        full = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
        net.rnn_clear_previous_state()
        for t in range(T):
            h = np.zeros((1, V, 1), np.float32)
            h[0, ids[0, t], 0] = 1.0
            got = np.asarray(net.rnn_time_step(h))
            np.testing.assert_allclose(got[0, :, 0], full[0, :, t],
                                       atol=1e-4, err_msg=f"pos {t}")


class TestWindowLayer:
    def test_window_streaming_matches_full(self):
        layer = SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                   activation="identity", window=3,
                                   cache_length=10)
        p, _ = layer.init(jax.random.PRNGKey(9), InputType.recurrent(16, 8))
        x = jnp.asarray(RNG.standard_normal((1, 16, 8)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        state, outs = {}, []
        for t in range(8):
            y, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
            outs.append(np.asarray(y)[:, :, 0])
        np.testing.assert_allclose(np.stack(outs, -1), np.asarray(full),
                                   atol=1e-4)

    def test_window_serde(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            layer_from_dict, layer_to_dict,
        )
        layer = SelfAttentionLayer(n_out=16, window=128)
        assert layer_from_dict(layer_to_dict(layer)).window == 128

    def test_bad_window_rejected_at_init(self):
        for bad_kw in ({"causal": False, "window": 4}, {"window": 0}):
            layer = SelfAttentionLayer(n_out=16, n_heads=2, **bad_kw)
            with pytest.raises(ValueError, match="window|causal"):
                layer.init(jax.random.PRNGKey(0),
                           InputType.recurrent(16, 8))


class TestRollingWindowStreaming:
    """Windowed streaming with a rolling cache: unbounded generation with
    bounded memory (cache_length >= window)."""

    def _layer(self, W=4, L=6, rope=False):
        layer = SelfAttentionLayer(n_out=16, n_heads=2, causal=True,
                                   activation="identity", window=W,
                                   cache_length=L, rope=rope)
        p, _ = layer.init(jax.random.PRNGKey(11),
                          InputType.recurrent(16, 8))
        return layer, p

    @pytest.mark.parametrize("rope", [False, True])
    def test_streaming_past_cache_matches_full(self, rope):
        # stream T=16 tokens through an L=6 cache: far past capacity —
        # the rolling slots must keep every in-window key resident
        layer, p = self._layer(W=4, L=6, rope=rope)
        T = 16
        x = jnp.asarray(RNG.standard_normal((1, 16, T)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        state, outs = {}, []
        for t in range(T):
            y, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
            outs.append(np.asarray(y)[:, :, 0])
        np.testing.assert_allclose(np.stack(outs, -1), np.asarray(full),
                                   atol=1e-4)

    def test_chunked_priming_with_wrap(self):
        # prime with a chunk, then single steps crossing the wrap boundary
        layer, p = self._layer(W=3, L=4)
        T = 11
        x = jnp.asarray(RNG.standard_normal((1, 16, T)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        y, state = layer.apply(p, x[:, :, :4], {}, stream=True)
        got = [np.asarray(y)]
        for t in range(4, T):
            y, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
            got.append(np.asarray(y))
        np.testing.assert_allclose(np.concatenate(got, -1),
                                   np.asarray(full), atol=1e-4)

    def test_no_stream_budget_limit(self):
        # windowed layers are exempt from the capacity guard: a network of
        # them streams arbitrarily long
        from deeplearning4j_tpu.nn.conf.layers import check_stream_budget

        class Net:
            pass

        layer, _ = self._layer(W=4, L=6)
        net = Net()
        for _ in range(10):          # 10 x 8 positions >> cache_length 6
            check_stream_budget(net, 8, [layer])

    def test_cache_smaller_than_window_rejected(self):
        layer, p = self._layer(W=8, L=4)
        x = jnp.asarray(RNG.standard_normal((1, 16, 1)), jnp.float32)
        with pytest.raises(ValueError, match="cache_length >= window"):
            layer.apply(p, x, {}, stream=True)

    def test_midstream_chunk_eviction_rejected(self):
        # the reviewer's trace: W=3, L=4, positions 0-3 streamed singly,
        # then a 3-token chunk would overwrite slot 2 (key 2, still in
        # position 4's window) before attending — must be rejected
        layer, p = self._layer(W=3, L=4)
        x = jnp.asarray(RNG.standard_normal((1, 16, 7)), jnp.float32)
        state = {}
        for t in range(4):
            _, state = layer.apply(p, x[:, :, t:t + 1], state, stream=True)
        with pytest.raises(ValueError, match="evict in-window"):
            layer.apply(p, x[:, :, 4:7], state, stream=True)

    def test_midstream_chunk_at_safe_bound_matches_full(self):
        # chunks up to L - W + 1 positions are safe mid-stream
        layer, p = self._layer(W=3, L=6)   # safe chunk = 4
        T = 12
        x = jnp.asarray(RNG.standard_normal((1, 16, T)), jnp.float32)
        full, _ = layer.apply(p, x, {})
        y, state = layer.apply(p, x[:, :, :4], {}, stream=True)
        got = [np.asarray(y)]
        for s0 in range(4, T, 4):
            y, state = layer.apply(p, x[:, :, s0:s0 + 4], state,
                                   stream=True)
            got.append(np.asarray(y))
        np.testing.assert_allclose(np.concatenate(got, -1),
                                   np.asarray(full), atol=1e-4)


def test_zoo_window_passthrough():
    model = TextGenerationTransformer(vocab_size=8, embed_dim=16, n_heads=2,
                                      n_layers=1, max_length=16, window=8)
    conf = model.conf()
    assert conf.vertices["attn0"].layer.window == 8


class TestBeamSearch:
    """Beam search on the streaming KV-cache machinery: beams ride the
    batch dim; pruning gathers carried state (reorder_stream_state)."""

    def _net(self, **kw):
        model = TextGenerationTransformer(vocab_size=10, embed_dim=16,
                                          n_heads=2, n_layers=2,
                                          max_length=20, **kw)
        return model, model.init()

    def test_beam1_equals_greedy_stream(self):
        # width-1 beam == greedy argmax decoding step by step
        model, net = self._net()
        ids, score = model.beam_search(net, [1, 2], steps=6, beam_width=1)
        assert len(ids) == 8 and np.isfinite(score)

        net.rnn_clear_previous_state()
        x = np.zeros((1, 10, 2), np.float32)
        x[0, [1, 2], np.arange(2)] = 1.0
        out = net.rnn_time_step(x)
        greedy = [1, 2]
        for _ in range(6):
            probs = np.asarray(out[0] if isinstance(out, (list, tuple))
                               else out)[0, :, -1]
            nxt = int(probs.argmax())
            greedy.append(nxt)
            h = np.zeros((1, 10, 1), np.float32)
            h[0, nxt, 0] = 1.0
            out = net.rnn_time_step(h)
        assert ids == greedy[:len(ids)]

    def test_beam_score_is_sequence_logprob(self):
        # the returned score must equal the sum of the model's stepwise
        # log-probs for the returned continuation (teacher-forced check)
        model, net = self._net()
        seed = [3, 1]
        ids, score = model.beam_search(net, seed, steps=5, beam_width=3)
        cont = ids[len(seed):]
        x = np.zeros((1, 10, len(ids)), np.float32)
        x[0, ids, np.arange(len(ids))] = 1.0
        out = net.output(x)
        probs = np.asarray(out[0] if isinstance(out, (list, tuple))
                           else out)[0]
        lp = sum(np.log(probs[tok, len(seed) - 1 + t])
                 for t, tok in enumerate(cont))
        np.testing.assert_allclose(score, lp, atol=1e-3)

    def test_full_width_beam_is_exhaustive_optimum(self):
        # beam width == vocab with 2 steps retains every step-1 prefix,
        # so the search is exhaustive: its best sequence must equal the
        # argmax over all V^2 continuations (teacher-forced brute force)
        model, net = self._net()
        V, seed = 10, [2, 5]
        ids, score = model.beam_search(net, seed, steps=2, beam_width=V)

        best_lp, best_seq = -np.inf, None
        for a in range(V):
            for b in range(V):
                full = seed + [a, b]
                x = np.zeros((1, V, 4), np.float32)
                x[0, full, np.arange(4)] = 1.0
                out = net.output(x)
                p = np.asarray(out[0] if isinstance(out, (list, tuple))
                               else out)[0]
                lp = np.log(p[a, 1]) + np.log(p[b, 2])
                if lp > best_lp:
                    best_lp, best_seq = lp, full
        assert ids == best_seq
        np.testing.assert_allclose(score, best_lp, atol=1e-3)

    def test_steps_zero_rejected(self):
        model, net = self._net()
        with pytest.raises(ValueError, match="steps"):
            model.beam_search(net, [1], steps=0)

    def test_beam_width_clamped_to_vocab(self):
        model, net = self._net()
        ids, score = model.beam_search(net, [1], steps=3, beam_width=50)
        assert len(ids) == 4 and np.isfinite(score)

    def test_beam_search_with_rope_gqa_window(self):
        model, net = self._net(positional="rope", n_kv_heads=1, window=6)
        ids, score = model.beam_search(net, [1], steps=10, beam_width=3)
        assert len(ids) == 11 and np.isfinite(score)

    def test_lstm_beam_search(self):
        # the same decoder drives the reference-era LSTM LM through its
        # stored-state rnnTimeStep path (h/c carried, unbounded length)
        from deeplearning4j_tpu.zoo import TextGenerationLSTM
        model = TextGenerationLSTM(vocab_size=9, hidden=16, layers=1,
                                   max_length=12)
        net = model.init()
        ids, score = model.beam_search(net, [1, 4], steps=20, beam_width=3)
        assert len(ids) == 22 and np.isfinite(score) and score < 0

    def test_lstm_beam_score_is_sequence_logprob(self):
        from deeplearning4j_tpu.zoo import TextGenerationLSTM
        model = TextGenerationLSTM(vocab_size=9, hidden=16, layers=1,
                                   max_length=16)
        net = model.init()
        seed = [2, 7]
        ids, score = model.beam_search(net, seed, steps=4, beam_width=3)
        x = np.zeros((1, 9, len(ids)), np.float32)
        x[0, ids, np.arange(len(ids))] = 1.0
        out = net.output(x)
        probs = np.asarray(out[0] if isinstance(out, (list, tuple))
                           else out)[0]
        lp = sum(np.log(probs[tok, len(seed) - 1 + t])
                 for t, tok in enumerate(ids[len(seed):]))
        np.testing.assert_allclose(score, lp, atol=1e-3)


def test_sample_and_sample_stream_identical_sequences():
    """User-level lock on streaming==full: with identically seeded RNGs,
    the padded full-forward sampler and the KV-cache streaming sampler
    must emit the SAME token sequence."""
    model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                      n_heads=2, n_layers=2, max_length=16)
    net = model.init()
    a = model.sample(net, [1, 2, 3], steps=8, temperature=0.8,
                     rng=np.random.default_rng(42))
    b = model.sample_stream(net, [1, 2, 3], steps=8, temperature=0.8,
                            rng=np.random.default_rng(42))
    assert a == b


def test_lstm_sample_stream():
    from deeplearning4j_tpu.zoo import TextGenerationLSTM
    model = TextGenerationLSTM(vocab_size=9, hidden=16, layers=1,
                               max_length=8)
    net = model.init()
    ids = model.sample_stream(net, [1, 2], steps=20, temperature=0.9,
                              rng=np.random.default_rng(5))
    assert len(ids) == 22                   # unbounded by max_length
    assert all(0 <= i < 9 for i in ids)


class TestStreamingMask:
    """Key masks in streaming decode: carried in the KV cache so padded
    positions stay masked on later steps (the non-stream path key-masks
    them; pre-fix the stream path silently ignored the mask)."""

    def _net(self, **kw):
        conf = (NeuralNetConfiguration.Builder().seed(7).list()
                .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                          causal=True, cache_length=16,
                                          activation="identity", **kw))
                .layer(RnnOutputLayer(n_in=8, n_out=5, loss="mcxent",
                                      activation="softmax"))
                .set_input_type(InputType.recurrent(8, 16))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_masked_streaming_matches_full_forward(self):
        net = self._net()
        x = RNG.standard_normal((2, 8, 7)).astype(np.float32)
        # row 0 fully valid; row 1 padded at positions 4,5 then a valid
        # token at 6 — the streamed cache must keep 4,5 masked forever
        mask = np.array([[1, 1, 1, 1, 1, 1, 1],
                         [1, 1, 1, 1, 0, 0, 1]], np.float32)
        full = np.asarray(net.output(x, mask=mask))

        net.rnn_clear_previous_state()
        got = np.asarray(net.rnn_time_step(x[:, :, :6], mask=mask[:, :6]))
        np.testing.assert_allclose(got[0], full[0, :, :6], atol=1e-5)
        np.testing.assert_allclose(got[1, :, :4], full[1, :, :4], atol=1e-5)
        got = np.asarray(net.rnn_time_step(x[:, :, 6:7], mask=mask[:, 6:7]))
        np.testing.assert_allclose(got[:, :, 0], full[:, :, 6], atol=1e-5)

    def test_masked_streaming_rolling_window(self):
        net = self._net(window=4)
        x = RNG.standard_normal((2, 8, 6)).astype(np.float32)
        mask = np.array([[1, 1, 1, 1, 1, 1],
                         [1, 1, 1, 0, 1, 1]], np.float32)
        full = np.asarray(net.output(x, mask=mask))
        net.rnn_clear_previous_state()
        got = np.asarray(net.rnn_time_step(x[:, :, :3], mask=mask[:, :3]))
        np.testing.assert_allclose(got, full[:, :, :3], atol=1e-5)
        for t in range(3, 6):
            got = np.asarray(net.rnn_time_step(x[:, :, t:t + 1],
                                               mask=mask[:, t:t + 1]))
            np.testing.assert_allclose(got[:, :, 0], full[:, :, t],
                                       atol=1e-5, err_msg=f"position {t}")

    def test_mask_midstream_after_unmasked_start_rejected(self):
        net = self._net()
        x = RNG.standard_normal((1, 8, 2)).astype(np.float32)
        net.rnn_time_step(x)                       # unmasked start
        with pytest.raises(ValueError, match="mid-stream"):
            net.rnn_time_step(x, mask=np.ones((1, 2), np.float32))

    def test_unmasked_stream_unchanged(self):
        """No mask anywhere: state carries no kv_mask buffer (existing
        decode paths keep their shapes/cost)."""
        net = self._net()
        x = RNG.standard_normal((1, 8, 2)).astype(np.float32)
        net.rnn_time_step(x)
        assert not any("kv_mask" in s for s in net.state.values()
                       if isinstance(s, dict))


class TestStreamBudgetCommit:
    def test_rejected_call_does_not_inflate_budget(self):
        """An oversized rnn_time_step raises BEFORE committing its length,
        so later within-capacity calls still work (pre-fix the counter
        inflated permanently)."""
        model = TextGenerationTransformer(vocab_size=8, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=4)
        net = model.init()
        big = np.zeros((1, 8, 6), np.float32)
        big[0, 0, :] = 1.0
        with pytest.raises(ValueError, match="streaming capacity"):
            net.rnn_time_step(big)
        small = np.zeros((1, 8, 1), np.float32)
        small[0, 0, 0] = 1.0
        for _ in range(4):                 # full capacity still available
            net.rnn_time_step(small)
        with pytest.raises(ValueError, match="streaming capacity"):
            net.rnn_time_step(small)

    def test_forward_error_does_not_inflate_budget(self):
        """A forward-raised error (mid-stream mask) must not commit the
        chunk to the stream counter — the KV cache was never updated."""
        conf = (NeuralNetConfiguration.Builder().seed(7).list()
                .layer(SelfAttentionLayer(n_in=8, n_out=8, n_heads=2,
                                          causal=True, cache_length=4))
                .layer(RnnOutputLayer(n_in=8, n_out=5, loss="mcxent",
                                      activation="softmax"))
                .set_input_type(InputType.recurrent(8, 16))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.standard_normal((1, 8, 2)).astype(np.float32)
        net.rnn_time_step(x)                       # budget 2
        with pytest.raises(ValueError, match="mid-stream"):
            net.rnn_time_step(x, mask=np.ones((1, 2), np.float32))
        net.rnn_time_step(x)                       # budget 4, cache holds 4
        with pytest.raises(ValueError, match="streaming capacity"):
            net.rnn_time_step(x)


class TestBucketedDecoding:
    """Serving-grade jit-shape bucketing (VERDICT r2: beam search retraced
    per (beam width, prompt length)): prompts prime in power-of-two
    chunks and beam batches pad to power-of-two buckets, so new widths /
    lengths reuse warm compiled shapes."""

    def _net(self):
        model = TextGenerationTransformer(vocab_size=12, embed_dim=16,
                                          n_heads=2, n_layers=1,
                                          max_length=64)
        return model, model.init()

    def _stream_traces(self, net):
        from deeplearning4j_tpu.nn.conf import layers as L
        fn = net._jit_cache.get(
            ("rnn_step", False, False, net.conf.dtype,
             L._STREAM_CACHE_SHARDING, L._PAGED_DECODE_IMPL))
        assert fn is not None, "rnn_step jit key drifted from the tests"
        return fn._cache_size()

    def test_prime_chunks(self):
        from deeplearning4j_tpu.util.decoding import _prime_chunks
        assert _prime_chunks(1) == [1]
        assert _prime_chunks(5) == [4, 1]
        assert _prime_chunks(6) == [4, 2]
        assert _prime_chunks(64) == [64]
        assert _prime_chunks(100) == [64, 32, 4]
        assert sum(_prime_chunks(37)) == 37

    def test_prime_chunk_max_configurable(self):
        """Long-prompt serving can raise the chunk cap: fewer dispatches,
        identical decode output (chunks are exact slices, never padded)."""
        from deeplearning4j_tpu.util import decoding
        prev = decoding.PRIME_CHUNK_MAX
        assert decoding._prime_chunks(1000)[0] == prev  # default cap
        try:
            decoding.set_prime_chunk_max(1024)
            chunks = decoding._prime_chunks(1000)
            assert chunks == [512, 256, 128, 64, 32, 8]
            model, net = self._net()
            big = model.sample_stream(net, [1, 2, 3, 4, 5], steps=4)
            decoding.set_prime_chunk_max(4)
            model2, net2 = self._net()
            small = model2.sample_stream(net2, [1, 2, 3, 4, 5], steps=4)
            assert big == small
        finally:
            decoding.set_prime_chunk_max(prev)
        import pytest
        with pytest.raises(ValueError):
            decoding.set_prime_chunk_max(48)

    def test_prime_chunk_max_per_call(self):
        """The per-call override scopes to one decode and leaves the
        process default untouched."""
        from deeplearning4j_tpu.util import decoding
        prev = decoding.PRIME_CHUNK_MAX
        model, net = self._net()
        a = model.sample_stream(net, [1, 2, 3, 4, 5], steps=4)
        model2, net2 = self._net()
        b = decoding.sample_stream(net2, [1, 2, 3, 4, 5], steps=4,
                                   vocab_size=12, prime_chunk_max=2)
        assert a == b
        assert decoding.PRIME_CHUNK_MAX == prev
        import pytest
        with pytest.raises(ValueError):
            decoding.sample_stream(net2, [1, 2, 3], steps=1, vocab_size=12,
                                   prime_chunk_max=3)

    def test_beam_widths_share_bucket_traces(self):
        from deeplearning4j_tpu.util.decoding import beam_search
        model, net = self._net()
        beam_search(net, [1, 2, 3, 4, 5], steps=4, vocab_size=12,
                    beam_width=3, max_length=64)
        warm = self._stream_traces(net)
        # same bucket (4) + new prompt length 6 = [4, 2]: exactly one
        # new chunk shape may compile, nothing else
        beam_search(net, [1, 2, 3, 4, 5, 6], steps=4, vocab_size=12,
                    beam_width=4, max_length=64)
        assert self._stream_traces(net) <= warm + 1
        # swapped (width, length) combinations: fully warm, ZERO retraces
        now = self._stream_traces(net)
        beam_search(net, [2, 3, 4, 5, 6], steps=3, vocab_size=12,
                    beam_width=4, max_length=64)
        beam_search(net, [1, 2, 3, 4, 5, 6], steps=3, vocab_size=12,
                    beam_width=3, max_length=64)
        assert self._stream_traces(net) == now

    def test_sample_stream_prompt_lengths_share_traces(self):
        model, net = self._net()
        model.sample_stream(net, [1, 2, 3, 4, 5], steps=3)
        warm = self._stream_traces(net)
        net2 = net  # same process, different prompt length, same bucket set
        model.sample_stream(net2, [2, 3, 4, 5, 6], steps=3)
        assert self._stream_traces(net2) == warm

    def test_bucketed_beam_equals_exhaustive_top1(self):
        """Semantics unchanged by bucketing: width V beam == greedy
        max-prob path (the old exhaustive invariant)."""
        from deeplearning4j_tpu.util.decoding import beam_search
        model, net = self._net()
        seq, score = beam_search(net, [1, 2], steps=3, vocab_size=12,
                                 beam_width=3, max_length=64)
        assert len(seq) == 5 and all(0 <= t < 12 for t in seq)
        assert np.isfinite(score)
        # deterministic across repeated calls (state fully reset)
        seq2, score2 = beam_search(net, [1, 2], steps=3, vocab_size=12,
                                   beam_width=3, max_length=64)
        assert seq == seq2 and np.isclose(score, score2)
