"""Engine builder for the cross-process fleet worker tests/bench.

``serving/fleet/worker.py`` spawns replica processes with
``--builder tests.fleet_proc_builder:build``: every process calls
:func:`build` and gets a bit-identically parameterized engine (fixed
default init seed, same constructor args) — the homogeneous-replica
contract that makes any replica continue any stream bit-exactly.
"""

V = 12


def net():
    from deeplearning4j_tpu.zoo import TextGenerationTransformer
    return TextGenerationTransformer(
        vocab_size=V, embed_dim=16, n_heads=2, n_layers=2,
        max_length=64, positional="rope").init()


def build(rid):
    from deeplearning4j_tpu.serving import GenerationEngine
    return GenerationEngine(net(), V, slots=4)


def build_paged(rid):
    """Paged-KV builder for the disaggregated fleet: page shipping
    requires a page pool on BOTH roles (prefill exports pages, decode
    imports them). Same net, same seed — homogeneous by contract."""
    from deeplearning4j_tpu.serving import GenerationEngine, PagedKVConfig
    return GenerationEngine(
        net(), V, slots=4,
        paging=PagedKVConfig(page_size=8, total_pages=64))
