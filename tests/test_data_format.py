"""Internal NHWC layout mode: numerical equivalence with the NCHW default.

The public API stays NCHW (inputs [N,C,H,W], weights [O,I,kH,kW], flat
feature order); use_cnn_data_format("NHWC") only changes the internal
activation layout, so outputs and training trajectories must match the
NCHW run to float tolerance.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import (
    MultiLayerConfiguration, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd


def _small_cnn_conf():
    return (NeuralNetConfiguration.Builder()
            .seed(7)
            .updater(Sgd(0.05))
            .list()
            .layer(L.ConvolutionLayer(n_out=8, kernel=(3, 3), stride=(1, 1),
                                      convolution_mode="same",
                                      activation="relu"))
            .layer(L.BatchNormalization())
            .layer(L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(L.LocalResponseNormalization(n=3))
            .layer(L.ZeroPaddingLayer(padding=(1, 1, 1, 1)))
            .layer(L.Upsampling2DLayer(size=(2, 2)))
            .layer(L.GlobalPoolingLayer(pooling_type="avg"))
            .layer(L.OutputLayer(n_out=5, activation="softmax",
                                 loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(12, 12, 3))
            .build())


def _data(n=4, c=3, h=12, w=12, k=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, c, h, w)).astype(np.float32)
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.integers(0, k, n)] = 1.0
    return x, y


class TestMultiLayerNhwc:
    def test_output_equivalence(self):
        x, _ = _data()
        net_a = MultiLayerNetwork(_small_cnn_conf()).init()
        net_b = MultiLayerNetwork(
            _small_cnn_conf().use_cnn_data_format("NHWC")).init()
        ya = np.asarray(net_a.output(x))
        yb = np.asarray(net_b.output(x))
        np.testing.assert_allclose(ya, yb, atol=1e-5)

    def test_training_equivalence(self):
        x, y = _data()
        net_a = MultiLayerNetwork(_small_cnn_conf()).init()
        net_b = MultiLayerNetwork(
            _small_cnn_conf().use_cnn_data_format("NHWC")).init()
        net_a.fit(x, y, epochs=3, batch_size=4)
        net_b.fit(x, y, epochs=3, batch_size=4)
        np.testing.assert_allclose(np.asarray(net_a.output(x)),
                                   np.asarray(net_b.output(x)), atol=1e-4)

    def test_json_roundtrip_preserves_format(self):
        conf = _small_cnn_conf().use_cnn_data_format("NHWC")
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.layers[0].data_format == "NHWC"
        assert conf2.preprocessors[0].data_format == "NHWC"

    def test_cnn_input_to_dense_entry_flatten_stays_nchw(self):
        """CNN input straight into a dense layer: the entry CnnToFF
        preprocessor consumes the public NCHW input and must keep DL4J
        flat order even when the net is switched to NHWC."""
        def conf():
            return (NeuralNetConfiguration.Builder()
                    .seed(5).updater(Sgd(0.1)).list()
                    .layer(L.DenseLayer(n_out=6, activation="relu"))
                    .layer(L.OutputLayer(n_out=3, activation="softmax",
                                         loss="negativeloglikelihood"))
                    .set_input_type(InputType.convolutional(4, 4, 2))
                    .build())
        x = np.random.default_rng(2).standard_normal(
            (3, 2, 4, 4)).astype(np.float32)
        net_a = MultiLayerNetwork(conf()).init()
        net_b = MultiLayerNetwork(conf().use_cnn_data_format("NHWC")).init()
        np.testing.assert_allclose(np.asarray(net_a.output(x)),
                                   np.asarray(net_b.output(x)), atol=1e-6)

    def test_one_pass_bn_large_mean_no_nan(self):
        """fp32 cancellation in E[x^2]-mean^2 must not NaN the rsqrt."""
        from deeplearning4j_tpu.nn.layers.normalization import batch_norm
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        x = jnp.asarray(1000.0 + 1e-3 * rng.standard_normal((8, 4, 16, 16)),
                        jnp.float32)
        g = jnp.ones(4); b = jnp.zeros(4)
        y, m, v = batch_norm(x, g, b, jnp.zeros(4), jnp.ones(4), train=True)
        assert np.isfinite(np.asarray(y)).all()
        assert np.isfinite(np.asarray(v)).all() and (np.asarray(v) >= 0).all()


def _residual_graph_conf():
    return (NeuralNetConfiguration.Builder()
            .seed(3)
            .updater(Sgd(0.05))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.convolutional(8, 8, 3))
            .add_layer("c1", L.ConvolutionLayer(n_out=8, kernel=(3, 3),
                                                convolution_mode="same"),
                       "in")
            .add_layer("bn1", L.BatchNormalization(activation="relu"), "c1")
            .add_layer("c2", L.ConvolutionLayer(n_out=8, kernel=(3, 3),
                                                convolution_mode="same"),
                       "bn1")
            .add_vertex("res", ElementWiseVertex(op="add"), "bn1", "c2")
            .add_vertex("mrg", MergeVertex(), "res", "bn1")
            .add_layer("gp", L.GlobalPoolingLayer(pooling_type="avg"), "mrg")
            .add_layer("out", L.OutputLayer(n_out=4, activation="softmax",
                                            loss="negativeloglikelihood"),
                       "gp")
            .set_outputs("out")
            .build())


class TestGraphNhwc:
    def test_output_and_training_equivalence(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 3, 8, 8)).astype(np.float32)
        y = np.zeros((4, 4), np.float32)
        y[np.arange(4), rng.integers(0, 4, 4)] = 1.0

        net_a = ComputationGraph(_residual_graph_conf()).init()
        net_b = ComputationGraph(
            _residual_graph_conf().use_cnn_data_format("NHWC")).init()
        np.testing.assert_allclose(
            np.asarray(net_a.output(x)[0]), np.asarray(net_b.output(x)[0]),
            atol=1e-5)
        for _ in range(3):
            net_a._fit_batch(DataSet({"in": x}, {"out": y}))
            net_b._fit_batch(DataSet({"in": x}, {"out": y}))
        np.testing.assert_allclose(
            np.asarray(net_a.output(x)[0]), np.asarray(net_b.output(x)[0]),
            atol=1e-4)

    def test_subset_poolhelper_nhwc(self):
        """SubsetVertex/PoolHelperVertex slice the right axes under NHWC."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            PoolHelperVertex, SubsetVertex,
        )
        x_nchw = jnp.arange(2 * 6 * 4 * 4, dtype=jnp.float32
                            ).reshape(2, 6, 4, 4)
        x_nhwc = x_nchw.transpose(0, 2, 3, 1)
        sv_a = SubsetVertex(from_index=1, to_index=3)
        sv_b = SubsetVertex(from_index=1, to_index=3, data_format="NHWC")
        ya, _ = sv_a.apply({}, [x_nchw], {})
        yb, _ = sv_b.apply({}, [x_nhwc], {})
        np.testing.assert_allclose(np.asarray(ya),
                                   np.asarray(yb.transpose(0, 3, 1, 2)))
        ph_a = PoolHelperVertex()
        ph_b = PoolHelperVertex(data_format="NHWC")
        ya, _ = ph_a.apply({}, [x_nchw], {})
        yb, _ = ph_b.apply({}, [x_nhwc], {})
        np.testing.assert_allclose(np.asarray(ya),
                                   np.asarray(yb.transpose(0, 3, 1, 2)))

    def test_zoo_resnet_nhwc(self):
        from deeplearning4j_tpu.zoo import ResNet50
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 32, 32)).astype(np.float32)
        net_a = ResNet50(num_classes=7, height=32, width=32).init()
        net_b = ResNet50(num_classes=7, height=32, width=32,
                         data_format="NHWC").init()
        # same seed -> same params; outputs must agree across layouts
        ya = np.asarray(net_a.output(x)[0])
        yb = np.asarray(net_b.output(x)[0])
        np.testing.assert_allclose(ya, yb, atol=1e-4)


class TestZooNhwcEquivalence:
    """Every CNN zoo model must produce identical outputs under the
    internal NHWC mode — exercises format-aware Merge/Subset/PoolHelper
    vertices, LRN, and every preprocessor in real topologies."""

    @pytest.mark.parametrize("name,kwargs,in_shape", [
        ("LeNet", dict(num_classes=10), (2, 1, 28, 28)),
        ("SimpleCNN", dict(num_classes=5, height=48, width=48),
         (2, 3, 48, 48)),
        ("AlexNet", dict(num_classes=7, height=96, width=96),
         (2, 3, 96, 96)),
        ("VGG16", dict(num_classes=6, height=48, width=48), (2, 3, 48, 48)),
        ("VGG19", dict(num_classes=6, height=48, width=48), (2, 3, 48, 48)),
        ("GoogLeNet", dict(num_classes=8, height=64, width=64),
         (2, 3, 64, 64)),
        ("ResNet50", dict(num_classes=4, height=32, width=32),
         (2, 3, 32, 32)),
        ("InceptionResNetV1", dict(num_classes=5, height=96, width=96),
         (1, 3, 96, 96)),
        ("FaceNetNN4Small2", dict(num_classes=5), (1, 3, 96, 96)),
    ])
    def test_output_matches(self, name, kwargs, in_shape):
        import deeplearning4j_tpu.zoo as zoo
        cls = getattr(zoo, name)
        rng = np.random.default_rng(0)
        x = rng.standard_normal(in_shape).astype(np.float32)

        def out(net):
            o = net.output(x)
            return np.asarray(o[0] if isinstance(o, (list, tuple)) else o)

        a = out(cls(**kwargs).init())
        b = out(cls(**kwargs, data_format="NHWC").init())
        np.testing.assert_allclose(a, b, atol=2e-4,
                                   err_msg=f"{name} NHWC != NCHW")


class TestHybridPreprocessorsNhwc:
    def test_cnn_to_rnn_hybrid(self):
        """Conv -> CnnToRnn -> LSTM nets must be layout-invariant (the
        preprocessor converts back to NCHW flat order before the time
        reshape)."""
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToRnnPreProcessor,
        )

        def conf():
            b = (NeuralNetConfiguration.Builder()
                 .seed(3).updater(Sgd(0.1)).list()
                 .layer(L.ConvolutionLayer(n_out=4, kernel=(3, 3),
                                           convolution_mode="same",
                                           activation="relu"))
                 .layer(L.LSTM(n_out=6, activation="tanh"))
                 .layer(L.RnnOutputLayer(n_out=3, loss="mcxent",
                                         activation="softmax")))
            b.input_preprocessor(1, CnnToRnnPreProcessor(
                height=6, width=5, channels=4, timesteps=4))
            return b.set_input_type(
                InputType.convolutional(6, 5, 2)).build()

        x = np.random.default_rng(0).standard_normal(
            (8, 2, 6, 5)).astype(np.float32)
        a = MultiLayerNetwork(conf()).init()
        b = MultiLayerNetwork(conf().use_cnn_data_format("NHWC")).init()
        np.testing.assert_allclose(np.asarray(a.output(x)),
                                   np.asarray(b.output(x)), atol=1e-5)

    def test_rnn_to_cnn_hybrid(self):
        """LSTM -> RnnToCnn -> Conv nets: the preprocessor emits the
        internal layout."""
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            RnnToCnnPreProcessor,
        )

        def conf():
            b = (NeuralNetConfiguration.Builder()
                 .seed(4).updater(Sgd(0.1)).list()
                 .layer(L.LSTM(n_out=12, activation="tanh"))
                 .layer(L.ConvolutionLayer(n_out=3, kernel=(2, 2),
                                           convolution_mode="same",
                                           activation="relu"))
                 .layer(L.GlobalPoolingLayer(pooling_type="avg"))
                 .layer(L.OutputLayer(n_out=2, loss="mcxent",
                                      activation="softmax")))
            b.input_preprocessor(1, RnnToCnnPreProcessor(
                height=4, width=3, channels=1))
            return b.set_input_type(InputType.recurrent(5, 6)).build()

        x = np.random.default_rng(1).standard_normal(
            (2, 5, 6)).astype(np.float32)
        a = MultiLayerNetwork(conf()).init()
        b = MultiLayerNetwork(conf().use_cnn_data_format("NHWC")).init()
        np.testing.assert_allclose(np.asarray(a.output(x)),
                                   np.asarray(b.output(x)), atol=1e-5)
