"""Serving fleet (serving/fleet/ + the engine request-ledger seam):
routed == single-engine bit-exact for greedy AND sampled traces,
kill-a-replica mid-trace with every stream completing bit-identically
on the survivor (health-down and lease-expiry detection), ledger
export/import incl. the pop-to-seat `_seating` gap and the versioned
cross-process payload roundtrip, AdmissionQueue.snapshot placement
views, prefix-affinity routing with per-replica cache-hit evidence,
overload rebalance of the queued tail, autoscaler hysteresis (no
flapping under an oscillating load trace), replica-mode membership
leases/generations, and zero retraces per replica after warmup
including post-migration re-admits."""

import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import runtime
from deeplearning4j_tpu.monitoring.metrics import MetricsRegistry
from deeplearning4j_tpu.serving import (
    AdmissionQueue, AutoscaleConfig, EngineShutdown, FleetAutoscaler,
    FleetConfig, FleetMembership, FleetRouter, FleetSignals,
    GenerationEngine, GenerationRequest, LEDGER_VERSION,
    NoReplicaAvailable, PagedKVConfig, RequestLedgerEntry)
from deeplearning4j_tpu.serving.fleet.membership import REPLICA_ROLE
from deeplearning4j_tpu.resilience.elastic import LeaseLedger
from deeplearning4j_tpu.zoo import TextGenerationTransformer

V = 12
PROMPTS = [[1, 2, 3, 4, 5], [6, 7], [8, 9, 10, 1], [2, 4, 6]]


def _net(max_length=32):
    """A fresh net with the FIXED default seed: every call yields
    bit-identical params — the fleet homogeneity contract."""
    return TextGenerationTransformer(vocab_size=V, embed_dim=16,
                                     n_heads=2, n_layers=2,
                                     max_length=max_length,
                                     positional="rope").init()


def _factory(**engine_kw):
    def make(rid):
        return GenerationEngine(_net(), V, slots=2, **engine_kw)
    return make


def _submit_all(target, prompts=None, steps=5, sampled=False):
    hs = []
    for i, p in enumerate(prompts if prompts is not None
                          else PROMPTS):
        kw = (dict(temperature=1.3, top_p=0.9) if sampled
              else dict(top_k=1))
        hs.append(target.submit(p, steps=steps,
                                rng=np.random.default_rng(i), **kw))
    return hs


def _single_engine_outputs(prompts=None, steps=5, sampled=False,
                           slots=4, **engine_kw):
    eng = GenerationEngine(_net(), V, slots=slots, **engine_kw)
    hs = _submit_all(eng, prompts, steps, sampled)
    eng.run_until_idle()
    return [h.result(timeout=0) for h in hs]


# ---------------------------------------------------------------------
# the engine request-ledger seam (the supervisor/migration shared path)
# ---------------------------------------------------------------------
class TestRequestLedger:
    def test_export_phases_and_version(self):
        eng = GenerationEngine(_net(), V, slots=2)
        hs = _submit_all(eng, steps=6)
        for _ in range(2):
            eng.step()                  # 2 seated, 2 queued
        entries = eng.export_ledger(include_queued=True)
        assert [e.version for e in entries] == [LEDGER_VERSION] * 4
        assert [e.phase for e in entries] == \
            ["active", "active", "queued", "queued"]
        assert all(e.streamed for e in entries if e.phase == "active")
        assert not any(e.streamed for e in entries if e.phase == "queued")
        # non-mutating: the engine still finishes everything
        assert eng.export_ledger() == eng.export_ledger()
        eng.run_until_idle()
        assert all(h.done for h in hs)

    def test_export_includes_the_seating_window(self):
        """The pop-to-seat `_seating` request is part of the export —
        the PR 9 audit gap, closed the same way for migration."""
        eng = GenerationEngine(_net(), V, slots=2)
        req = GenerationRequest([1, 2], 3, top_k=1)
        eng._seating = req
        entries = eng.export_ledger()
        assert [e.phase for e in entries] == ["seating"]
        assert entries[0].request is req
        eng._seating = None

    def test_detach_releases_without_terminal_events(self):
        eng = GenerationEngine(_net(), V, slots=2,
                               paging=PagedKVConfig(page_size=4))
        hs = _submit_all(eng, steps=6)
        eng.step()
        entries = eng.detach_ledger()
        assert len(entries) == 4
        assert not any(h.done for h in hs)       # nobody failed
        assert eng.active_slots() == 0 and eng.queue_depth() == 0
        # every slot page returned (prefix-cache refs may stay resident)
        assert eng.page_pool.used_count() == len(eng.prefix_cache)
        assert eng.health()["draining"] is True
        with pytest.raises(EngineShutdown):
            eng.submit([1], steps=1)

    def test_admit_from_ledger_continues_bit_identical(self):
        """Export mid-trace from engine A, re-admit on a fresh engine
        B: every stream continues bit-identically (greedy and sampled)
        — the supervisor-recovery exactness, across engines."""
        for sampled in (False, True):
            want = _single_engine_outputs(steps=6, sampled=sampled)
            a = GenerationEngine(_net(), V, slots=4)
            hs = _submit_all(a, steps=6, sampled=sampled)
            for _ in range(2):
                a.step()
            entries = a.detach_ledger()
            b = GenerationEngine(_net(), V, slots=4)
            assert b.admit_from_ledger(entries) == 4
            b.run_until_idle()
            assert [h.result(timeout=0) for h in hs] == want

    def test_admit_overflow_rides_the_queue(self):
        """More survivors than free slots: the overflow requeues (past
        the limit if needed) and admits as slots free — nobody drops."""
        a = GenerationEngine(_net(), V, slots=4)
        hs = _submit_all(a, steps=6)
        a.step()
        entries = a.detach_ledger()
        b = GenerationEngine(_net(), V, slots=1, queue_limit=1)
        assert b.admit_from_ledger(entries) == 4
        assert b.queue_depth() == 3           # 1 seated, 3 riding
        b.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == \
            _single_engine_outputs(steps=6)

    def test_admit_refused_while_draining_or_broken(self):
        b = GenerationEngine(_net(), V, slots=2)
        b.drain(timeout=0.1)
        with pytest.raises(EngineShutdown):
            b.admit_from_ledger([])

    def test_payload_roundtrip_is_bit_identical(self):
        """The serialized (cross-process) ledger form: rng state,
        pending token, and committed ids survive payload() ->
        from_payload(), and the rebuilt request's continuation matches
        the unperturbed run exactly — sampled, so the rng state is
        load-bearing."""
        want = _single_engine_outputs(steps=6, sampled=True)
        a = GenerationEngine(_net(), V, slots=4)
        hs = _submit_all(a, steps=6, sampled=True)
        for _ in range(2):
            a.step()
        payloads = [e.payload() for e in a.detach_ledger()]
        import json
        payloads = json.loads(json.dumps(payloads))  # wire-safe
        entries = [RequestLedgerEntry.from_payload(p) for p in payloads]
        b = GenerationEngine(_net(), V, slots=4)
        b.admit_from_ledger(entries)
        b.run_until_idle()
        # fresh handles (the originals cannot cross a process): compare
        # the rebuilt streams' final ids against the unperturbed run
        got = sorted(e.request.handle.result(timeout=0)
                     for e in entries)
        assert got == sorted(want)
        assert all(not h.done for h in hs)   # originals untouched here

    def test_payload_version_gate(self):
        p = RequestLedgerEntry.capture(
            GenerationRequest([1, 2], 2), "queued").payload()
        p["version"] = LEDGER_VERSION + 1
        with pytest.raises(ValueError, match="newer"):
            RequestLedgerEntry.from_payload(p)

    def test_v1_traceless_payload_admits_cleanly(self):
        """Backward compatibility across the LEDGER_VERSION 2 bump: a
        v1 payload (no trace key) must admit and continue exactly as
        before — the trace layer starts fresh with an import marker
        instead of refusing the request."""
        want = _single_engine_outputs(steps=6, sampled=True)
        a = GenerationEngine(_net(), V, slots=4)
        hs = _submit_all(a, steps=6, sampled=True)
        for _ in range(2):
            a.step()
        payloads = [e.payload() for e in a.detach_ledger()]
        for p in payloads:                 # shape of a pre-ISSUE-15 peer
            del p["trace"]
            p["version"] = 1
        entries = [RequestLedgerEntry.from_payload(p) for p in payloads]
        b = GenerationEngine(_net(), V, slots=4)
        assert b.admit_from_ledger(entries) == 4
        b.run_until_idle()
        got = sorted(e.request.handle.result(timeout=0)
                     for e in entries)
        assert got == sorted(want)
        assert not any(h.done for h in hs)
        for e in entries:
            evs = [r["event"] for r in
                   e.request.handle.trace().events()]
            assert "imported" in evs       # fresh trace, marked

    def test_payload_carries_the_trace_across_the_wire(self):
        """v2 payloads ship the request trace: a cross-process
        continuation keeps the source-side history (submit, first
        token) instead of starting blind."""
        a = GenerationEngine(_net(), V, slots=4)
        hs = _submit_all(a, steps=6)
        a.step()
        import json
        payloads = json.loads(json.dumps(
            [e.payload() for e in a.detach_ledger()]))
        assert all(p["version"] == LEDGER_VERSION for p in payloads)
        entries = [RequestLedgerEntry.from_payload(p) for p in payloads]
        streamed = [e for e in entries if e.streamed]
        assert streamed
        for e in streamed:
            evs = [r["event"] for r in
                   e.request.handle.trace().events()]
            assert evs[0] == "submit" and "first_token" in evs
        assert hs  # originals keep their own (local) traces untouched

    def test_payload_json_safe_for_any_generator(self):
        """submit() accepts ANY numpy Generator; the wire form must
        survive json for non-default bit generators too (MT19937's
        state carries an ndarray key) and restore to the same draw
        stream."""
        import json
        req = GenerationRequest(
            [1, 2], 4, rng=np.random.Generator(np.random.MT19937(5)))
        req.rng.random()                  # advance off the seed state
        entry = RequestLedgerEntry.capture(req, "queued")
        wire = json.loads(json.dumps(entry.payload()))
        back = RequestLedgerEntry.from_payload(wire)
        assert back.request.rng.random() == req.rng.random()


# ---------------------------------------------------------------------
# AdmissionQueue.snapshot (the router's placement view)
# ---------------------------------------------------------------------
class TestQueueSnapshot:
    def test_depths_ages_and_nonmutation(self):
        q = AdmissionQueue(limit=8)
        t0 = time.monotonic()
        reqs = [GenerationRequest([1], 1, priority=p)
                for p in (0, 1, 1, 2)]
        for r in reqs:
            q.submit(r)
        snap = q.snapshot(now=t0 + 1.0)
        assert snap.depth == 4
        assert snap.per_priority == {0: 1, 1: 2, 2: 1}
        assert snap.oldest_wait_s == pytest.approx(1.0, abs=0.2)
        assert q.depth() == 4                  # nothing popped
        assert [r.priority for r in q.peek_all()] == [2, 1, 1, 0]
        assert q.depth() == 4                  # peek is non-mutating
        assert q.snapshot().per_priority == snap.per_priority

    def test_empty_snapshot(self):
        snap = AdmissionQueue().snapshot()
        assert snap.depth == 0 and snap.per_priority == {}
        assert snap.oldest_wait_s is None

    def test_requeue_bypasses_limit(self):
        q = AdmissionQueue(limit=1, policy="fail_fast")
        q.submit(GenerationRequest([1], 1))
        q.requeue(GenerationRequest([2], 1, priority=5))
        assert q.depth() == 2
        assert q.pop().priority == 5           # ordering preserved


# ---------------------------------------------------------------------
# acceptance: routed == single-engine bit-exact
# ---------------------------------------------------------------------
class TestFleetParity:
    @pytest.mark.parametrize("sampled", [False, True])
    def test_fleet_matches_single_engine(self, sampled):
        want = _single_engine_outputs(sampled=sampled)
        fleet = FleetRouter(_factory(), replicas=2,
                            registry=MetricsRegistry())
        hs = _submit_all(fleet, sampled=sampled)
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        # the trace actually spread over both replicas
        spread = {rid for rid, h in fleet.health()["replicas"].items()}
        assert len(spread) == 2
        fleet.shutdown()

    def test_paged_fleet_matches_one_shot(self):
        want = _single_engine_outputs(
            paging=PagedKVConfig(page_size=4))
        fleet = FleetRouter(
            _factory(paging=PagedKVConfig(page_size=4)), replicas=3,
            registry=MetricsRegistry())
        hs = _submit_all(fleet)
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        fleet.shutdown()


# ---------------------------------------------------------------------
# acceptance: kill a replica mid-trace, streams continue bit-identical
# ---------------------------------------------------------------------
class TestKillReplica:
    @pytest.mark.parametrize("sampled", [False, True])
    def test_mid_trace_death_continues_bit_identical(self, sampled):
        want = _single_engine_outputs(steps=8, sampled=sampled)
        reg = MetricsRegistry()
        fleet = FleetRouter(_factory(), replicas=2, registry=reg)
        hs = _submit_all(fleet, steps=8, sampled=sampled)
        for _ in range(2):
            fleet.step()               # both replicas mid-stream
        victim = fleet.replicas()[0]
        assert victim.engine.active_slots() > 0
        victim.engine._stop.set()      # simulated process death
        fleet.run_until_idle()         # poll detects + migrates
        assert [h.result(timeout=0) for h in hs] == want
        assert fleet.migrations == 1
        assert fleet.migrated_requests >= 1
        assert len(fleet.replicas()) == 1
        assert victim.rid not in fleet.health()["replicas"]
        # the migrated requests' traces record the hop: source ->
        # surviving replica, both engines in the replica list, and the
        # breakdown counts exactly one migration (tracing is ON by
        # default — nothing here enabled it)
        survivor = fleet.replicas()[0]
        migrated = [h for h in hs
                    if h.trace().breakdown()["migrations"] >= 1]
        assert len(migrated) == fleet.migrated_requests
        for h in migrated:
            hop = [r for r in h.trace().events()
                   if r["event"] == "migrate"][0]
            assert hop["source"] == victim.rid
            assert hop["target"] == survivor.rid
            assert hop["cause"] == "death"
            # both replicas by DISTINCT identity: factory-built
            # engines share the model label, so the router's rid
            # stamp (trace_identity = "label#rN") is what makes the
            # hop visible in the replica list
            assert victim.engine.trace_identity != \
                survivor.engine.trace_identity
            assert set(h.trace().replicas()) >= {
                victim.engine.trace_identity,
                survivor.engine.trace_identity}
        # the ops timeline shows the death + migration sequence
        tl = [(e.category, e.name) for e in fleet.timeline()]
        assert ("fleet", "replica_dead") in tl
        assert ("fleet", "migration") in tl
        fleet.shutdown()

    def test_death_with_queued_requests_migrates_them_too(self):
        """Active AND queued requests on the dead replica move: the
        whole host-side ledger survives the device, not just slots."""
        want = _single_engine_outputs(steps=6)
        fleet = FleetRouter(_factory(), replicas=2,
                            registry=MetricsRegistry())
        hs = _submit_all(fleet, steps=6)
        fleet.step()
        victim = max(fleet.replicas(),
                     key=lambda r: r.engine.queue_depth()
                     + r.engine.active_slots())
        victim.engine._stop.set()
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        fleet.shutdown()

    def test_lease_expiry_detects_a_hung_replica(self, tmp_path):
        """Death via the membership ledger: the engine object still
        answers is_healthy() (a hung process would too) but its lease
        stopped beating — the fleet declares it dead and migrates."""
        # ttl must outlast scheduler stalls under a loaded suite (the
        # healthy replica's heartbeat daemon must never miss a window)
        cfg = FleetConfig(membership_root=str(tmp_path),
                          lease_ttl_s=0.6)
        fleet = FleetRouter(_factory(), replicas=2, config=cfg,
                            registry=MetricsRegistry())
        want = _single_engine_outputs(steps=6)
        hs = _submit_all(fleet, steps=6)
        fleet.step()
        victim = fleet.replicas()[0]
        fleet.membership.lease(victim.rid).stall()
        time.sleep(1.0)                # let the lease lapse
        out = fleet.poll()
        assert out["dead"] == [victim.rid]
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        assert len(fleet.replicas()) == 1
        fleet.shutdown()

    def test_all_replicas_dead_raises_no_replica(self):
        fleet = FleetRouter(_factory(), replicas=1,
                            registry=MetricsRegistry())
        fleet.replicas()[0].engine._stop.set()
        with pytest.raises(NoReplicaAvailable):
            fleet.submit([1, 2], steps=2, top_k=1)
        fleet.shutdown()

    def test_last_replica_death_respawns_to_the_autoscaler_floor(self):
        """With an autoscaler configured, losing the LAST replica is a
        respawn + migration, not a bricked fleet: poll re-establishes
        min_replicas BEFORE migrating so the dead replica's ledger
        lands on the replacement and every stream continues
        bit-identically."""
        want = _single_engine_outputs(steps=8)
        fleet = FleetRouter(_factory(), replicas=1,
                            autoscale=AutoscaleConfig(min_replicas=1,
                                                      max_replicas=2),
                            registry=MetricsRegistry())
        hs = _submit_all(fleet, steps=8)
        for _ in range(2):
            fleet.step()
        fleet.replicas()[0].engine._stop.set()
        out = fleet.poll()
        assert len(out["respawned"]) == 1 and out["migrated"] >= 1
        assert len(fleet.replicas()) == 1
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        # the respawned fleet keeps serving new work too
        h = fleet.submit([1, 2, 3], steps=3, top_k=1,
                         rng=np.random.default_rng(9))
        fleet.run_until_idle()
        assert h.result(timeout=0)
        fleet.shutdown()


# ---------------------------------------------------------------------
# prefix-affinity placement
# ---------------------------------------------------------------------
class TestAffinityPlacement:
    def test_shared_system_prompts_route_to_one_replica(self):
        """Two prompt families (distinct leading blocks): each family
        sticks to ONE replica after its first placement, so that
        replica's prefix cache serves every later family member."""
        sys_a, sys_b = [3, 1, 2, 0], [7, 8, 9, 10]
        prompts = []
        for i in range(4):
            prompts.append(sys_a + [5 + (i % 3)])
            prompts.append(sys_b + [1 + (i % 3)])
        reg = MetricsRegistry()
        fleet = FleetRouter(
            _factory(paging=PagedKVConfig(page_size=4), queue_limit=16),
            replicas=2, registry=reg)
        hs = _submit_all(fleet, prompts=prompts, steps=4)
        fleet.run_until_idle()
        assert all(h.result(timeout=0) for h in hs)
        snap = reg.snapshot_compact()
        hits = snap.get(
            "dl4jtpu_fleet_affinity_hits_total{fleet=fleet}", 0)
        assert hits == len(prompts) - 2      # all but the 2 first-seen
        # per-replica evidence: BOTH replicas' prefix caches served
        # their family (hits >= 1 on each)
        per = [h["prefix_cache"]["hits"]
               for h in fleet.health()["replicas"].values()]
        assert all(v >= 1 for v in per) and len(per) == 2
        # family members routed consistently
        routed_a = snap.get(
            "dl4jtpu_fleet_routed_total{fleet=fleet,replica=0}", 0)
        routed_b = snap.get(
            "dl4jtpu_fleet_routed_total{fleet=fleet,replica=1}", 0)
        assert routed_a == routed_b == len(prompts) / 2
        fleet.shutdown()

    def test_affinity_off_spreads_by_load(self):
        fleet = FleetRouter(
            _factory(), replicas=2, config=FleetConfig(affinity=False),
            registry=MetricsRegistry())
        hs = _submit_all(fleet)
        fleet.run_until_idle()
        assert all(h.done for h in hs)
        assert len(fleet.health()["replicas"]) == 2
        assert fleet.health()["affinity_entries"] == 0
        fleet.shutdown()

    def test_dead_owner_affinity_remaps(self):
        """After the affinity owner dies, the fingerprint re-places on
        a survivor instead of pointing at a ghost."""
        sys_a = [3, 1, 2, 0, 4]
        fleet = FleetRouter(_factory(), replicas=2,
                            registry=MetricsRegistry())
        h0 = fleet.submit(sys_a + [5], steps=3, top_k=1,
                          rng=np.random.default_rng(0))
        owner = next(r for r in fleet.replicas()
                     if r.engine.queue_depth()
                     or r.engine.active_slots())
        fleet.run_until_idle()
        owner.engine._stop.set()
        fleet.poll()
        h1 = fleet.submit(sys_a + [7], steps=3, top_k=1,
                          rng=np.random.default_rng(1))
        fleet.run_until_idle()
        assert h0.result(timeout=0) and h1.result(timeout=0)
        fleet.shutdown()


# ---------------------------------------------------------------------
# overload rebalance (queued tail moves, actives stay)
# ---------------------------------------------------------------------
class TestOverloadRebalance:
    def test_queued_tail_moves_to_idle_replica(self):
        want = _single_engine_outputs(
            prompts=[[3, 1, 2, 0, i + 1] for i in range(6)], steps=4)
        cfg = FleetConfig(rebalance_queue_wait_s=0.0, affinity_block=4)
        fleet = FleetRouter(_factory(queue_limit=16), replicas=2,
                            config=cfg, registry=MetricsRegistry())
        # affinity pins every submit to one replica -> deep queue there
        prompts = [[3, 1, 2, 0, i + 1] for i in range(6)]
        hs = _submit_all(fleet, prompts=prompts, steps=4)
        loaded = max(fleet.replicas(),
                     key=lambda r: r.engine.queue_depth())
        assert loaded.engine.queue_depth() >= 4
        moved = fleet.poll()["rebalanced"]
        assert moved >= 1
        other = next(r for r in fleet.replicas()
                     if r.rid != loaded.rid)
        assert other.engine.queue_depth() + other.engine.active_slots() \
            >= moved
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        fleet.shutdown()

    def test_no_rebalance_without_margin(self):
        cfg = FleetConfig(rebalance_queue_wait_s=0.0,
                          rebalance_load_margin=100.0,
                          affinity_block=4)
        fleet = FleetRouter(_factory(queue_limit=16), replicas=2,
                            config=cfg, registry=MetricsRegistry())
        hs = _submit_all(fleet, prompts=[[3, 1, 2, 0, i + 1]
                                         for i in range(6)], steps=4)
        assert fleet.poll()["rebalanced"] == 0
        fleet.run_until_idle()
        assert all(h.done for h in hs)
        fleet.shutdown()


# ---------------------------------------------------------------------
# autoscaler: hysteresis (pure policy) + end-to-end scaling
# ---------------------------------------------------------------------
def _sig(replicas=1, slots=2, active=0, queued=0, free=None, brown=0):
    return FleetSignals(replicas=replicas, slots=slots, active=active,
                        queued=queued, free_page_frac=free,
                        brownout_max=brown)


class TestAutoscalerHysteresis:
    def test_oscillating_load_never_flaps(self):
        """A load trace alternating pressure/idle every tick can never
        sustain either streak: ZERO actions over the whole trace."""
        asc = FleetAutoscaler(AutoscaleConfig(
            max_replicas=4, out_ticks=3, in_ticks=3, cooldown_s=0.0))
        for t in range(60):
            s = _sig(replicas=2, queued=8 if t % 2 else 0,
                     active=2 if t % 2 else 0)
            assert asc.decide(s, now=float(t)) is None
        assert asc.decisions == 0

    def test_sustained_pressure_scales_out_once_per_cooldown(self):
        asc = FleetAutoscaler(AutoscaleConfig(
            max_replicas=4, out_ticks=3, cooldown_s=10.0))
        got = [asc.decide(_sig(replicas=2, queued=8), now=float(t))
               for t in range(9)]
        assert got.count("out") == 1        # once, then cooldown gates
        assert got[2] == "out"              # on the 3rd consecutive tick

    def test_page_pressure_and_brownout_are_out_signals(self):
        asc = FleetAutoscaler(AutoscaleConfig(out_ticks=1,
                                              cooldown_s=0.0))
        assert asc.decide(_sig(free=0.05), now=0.0) == "out"
        asc2 = FleetAutoscaler(AutoscaleConfig(out_ticks=1,
                                               cooldown_s=0.0))
        assert asc2.decide(_sig(brown=2), now=0.0) == "out"

    def test_idle_scales_in_only_down_to_min(self):
        asc = FleetAutoscaler(AutoscaleConfig(
            min_replicas=1, in_ticks=2, cooldown_s=0.0))
        assert asc.decide(_sig(replicas=2), now=0.0) is None
        assert asc.decide(_sig(replicas=2), now=1.0) == "in"
        asc2 = FleetAutoscaler(AutoscaleConfig(
            min_replicas=1, in_ticks=1, cooldown_s=0.0))
        assert asc2.decide(_sig(replicas=1), now=0.0) is None  # at min

    def test_action_resets_streaks(self):
        asc = FleetAutoscaler(AutoscaleConfig(out_ticks=2,
                                              cooldown_s=0.0))
        assert asc.decide(_sig(queued=8), now=0.0) is None
        assert asc.decide(_sig(queued=8), now=1.0) == "out"
        # pressure persists but the streak restarted post-action
        assert asc.decide(_sig(replicas=2, queued=8), now=2.0) is None


class TestFleetScaling:
    def test_pressure_scales_out_and_idle_scales_in(self):
        made = []

        def factory(rid):
            made.append(rid)
            return GenerationEngine(_net(), V, slots=2, queue_limit=32)

        fleet = FleetRouter(
            factory, replicas=1,
            autoscale=AutoscaleConfig(min_replicas=1, max_replicas=2,
                                      out_ticks=2, in_ticks=2,
                                      cooldown_s=0.0),
            registry=MetricsRegistry())
        prompts = [[1 + i % 9, 2, 3] for i in range(8)]
        hs = _submit_all(fleet, prompts=prompts, steps=4)
        fleet.poll()
        assert fleet.poll()["scaled"] == "out"      # sustained 2 ticks
        assert len(fleet.replicas()) == 2 and made == [0, 1]
        fleet.run_until_idle()
        assert all(h.result(timeout=0) for h in hs)
        # run_until_idle's trailing poll already banked an idle tick;
        # the next poll(s) complete the in-streak
        scaled = [fleet.poll()["scaled"] for _ in range(2)]
        assert "in" in scaled
        assert len(fleet.replicas()) == 1
        assert fleet.scale_events == 2
        fleet.shutdown()

    def test_scale_in_migrates_in_flight_work(self):
        want = _single_engine_outputs(steps=8)
        fleet = FleetRouter(_factory(), replicas=2,
                            registry=MetricsRegistry())
        hs = _submit_all(fleet, steps=8)
        for _ in range(2):
            fleet.step()
        report = fleet.scale_in()                   # planned drain
        assert report is not None and report.cause == "scale_in"
        assert len(fleet.replicas()) == 1
        fleet.run_until_idle()
        assert [h.result(timeout=0) for h in hs] == want
        fleet.shutdown()

    def test_scale_in_refuses_last_replica(self):
        fleet = FleetRouter(_factory(), replicas=1,
                            registry=MetricsRegistry())
        assert fleet.scale_in() is None
        assert len(fleet.replicas()) == 1
        fleet.shutdown()


# ---------------------------------------------------------------------
# replica-mode membership (leases + generations)
# ---------------------------------------------------------------------
class TestFleetMembership:
    def test_leases_carry_the_replica_role(self, tmp_path):
        m = FleetMembership(str(tmp_path), ttl=5.0)
        m.join(0)
        m.join(1)
        lease = m.lease(0)
        assert lease.role == REPLICA_ROLE
        assert sorted(lease.live_ranks(role=REPLICA_ROLE)) == [0, 1]
        assert m.expired([0, 1]) == []
        m.stop()

    def test_train_role_leases_are_not_replicas(self, tmp_path):
        """A training rank sharing the ledger dir is never counted as
        a serving replica (and vice versa) — the role filter."""
        trainer = LeaseLedger(str(tmp_path), rank=7, ttl=5.0)
        trainer.heartbeat()
        m = FleetMembership(str(tmp_path), ttl=5.0)
        m.join(0)
        assert m.lease(0).live_ranks(role=REPLICA_ROLE) == [0]
        assert 7 in m.lease(0).live_ranks()         # unfiltered sees it
        assert m.expired([0]) == []
        m.stop()

    def test_expiry_and_generations(self, tmp_path):
        m = FleetMembership(str(tmp_path), ttl=0.5)
        m.join(0)
        m.join(1)
        g1 = m.publish([0, 1])
        m.lease(1).stall()
        time.sleep(0.8)
        assert m.expired([0, 1]) == [1]
        m.leave(1)
        g2 = m.publish([0])
        assert g2 == g1 + 1
        rec = m.record()
        assert rec.generation == g2 and list(rec.members) == [0]
        m.stop()

    def test_publish_race_republishes_at_the_successor(self, tmp_path):
        """Two routers sharing a root: the exclusive-create loser must
        RE-PUBLISH its own member set at the winner's successor — the
        on-disk record at the contested number describes the winner's
        fleet, not a membership the loser can adopt."""
        a = FleetMembership(str(tmp_path), ttl=5.0)
        b = FleetMembership(str(tmp_path), ttl=5.0)
        assert a.publish([0]) == 1
        assert b.publish([7]) == 2        # lost gen 1, converged at 2
        rec = b.record()
        assert rec.generation == 2 and list(rec.members) == [7]
        a.stop()
        b.stop()

    def test_in_process_mode_without_root(self):
        m = FleetMembership(None)
        m.join(0)
        assert not m.enabled and m.expired([0]) == []
        g = m.publish([0])
        assert g == 1 and m.record() is None
        m.stop()


# ---------------------------------------------------------------------
# acceptance: zero retraces per replica after warmup, incl. the
# post-migration re-admits
# ---------------------------------------------------------------------
def _compile_total():
    c = monitoring.global_registry().get(runtime.COMPILE_COUNTER)
    return 0.0 if c is None else c.total()


class TestNoRetraceAfterMigration:
    def test_kill_and_migrate_compile_nothing_new(self):
        """Full-envelope warmup on every replica, then a mid-trace
        kill + migration: the survivor's re-primes land in its warm
        prefill buckets and the continued decode reuses the compiled
        arena shapes — zero retraces across the whole episode (the
        PR 3 bar, applied to the fleet)."""
        monitoring.ensure_started()
        fleet = FleetRouter(_factory(), replicas=2,
                            registry=MetricsRegistry())
        fleet.warmup()              # every bucket up to capacity
        warm = _compile_total()
        hs = _submit_all(fleet, steps=6)
        for _ in range(2):
            fleet.step()
        fleet.replicas()[0].engine._stop.set()
        fleet.run_until_idle()
        assert all(h.result(timeout=0) for h in hs)
        assert fleet.migrations == 1
        assert _compile_total() == warm, (
            "fleet migration retraced after warmup — re-admits must "
            "reuse the survivor's warm prefill buckets and arena shapes")
        fleet.shutdown()
