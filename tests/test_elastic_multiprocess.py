"""REAL multi-process elastic training: kill a rank mid-run, prove the
survivor detects the loss, re-meshes to a smaller world, and resumes
BIT-EXACTLY from the last committed step; then prove the opposite
direction — a re-spawned rank is admitted at a commit boundary and the
fleet re-meshes back up.

The processes are genuine OS processes meeting through jax.distributed
(gloo over localhost — the DCN stand-in, same harness as
tests/test_distributed_multiprocess.py), and the kill is a genuine
SIGKILL from ``HostLossInjector``: nothing runs afterwards on the
victim, and the survivor's own coordination service would by default
TERMINATE it for the peer's death — surviving that cascade is the whole
point of the elastic layer (resilience/elastic.py +
parallel/elastic.py).

Acceptance pins (ISSUE 8):
- the survivor's final params are sha256-identical to an uninterrupted
  single-process run restored from the SAME committed step;
- zero new retraces in the survivor's post-re-mesh steady state;
- the dl4jtpu_elastic_* series are populated;
- the rejoin test restores world=2 and both ranks finish identical.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "elastic_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(*argv):
    repo_root = os.path.dirname(os.path.dirname(WORKER))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers force their own device count
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, *[str(a) for a in argv]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo_root)


def _finish(proc, timeout=420):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        out += "\n<<TIMEOUT KILLED>>"
    return out


def _load(path, log):
    assert os.path.exists(path), f"worker wrote no result:\n{log}"
    with open(path) as f:
        return json.load(f)


@pytest.mark.slow
def test_kill_one_rank_survivor_remeshes_bit_exact(tmp_path):
    """2-process fit; rank 1 SIGKILLed at global step 5 (after the
    step-4 commit). Rank 0 must detect the loss, re-mesh to world=1,
    resume from the committed step, and finish — with params identical
    to a single-process run restored from that same step."""
    ledger = str(tmp_path / "ledger")
    ckpt = str(tmp_path / "ckpt")
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"w{i}.json") for i in range(2)]
    steps, kill_at = 12, 5
    common = ["elastic", "--members", "0,1", "--coord", coord,
              "--ledger", ledger, "--ckpt", ckpt, "--steps", steps,
              "--commit-every", 2, "--kill-rank", 1,
              "--kill-step", kill_at]
    survivor = _spawn(*common, "--rank", 0, "--out", outs[0],
                      "--extend-steps", 4)
    victim = _spawn(*common, "--rank", 1, "--out", outs[1])

    v_log = _finish(victim)
    s_log = _finish(survivor)
    assert victim.returncode == -9, f"victim was not SIGKILLed:\n{v_log}"
    assert survivor.returncode == 0, f"survivor failed:\n{s_log}"

    res = _load(outs[0], s_log)
    h = res["health"]
    # the survivor re-meshed exactly once, down to a world of one
    assert h["remeshes"] == 1, s_log
    assert h["generation"] == 1 and h["world"] == 1, s_log
    assert h["members"] == [0] and h["process_id"] == 0
    assert res["iteration"] == steps + 4
    # it resumed from a step that was COMMITTED before the kill
    restored = res["restored_step"]
    assert restored is not None and 0 < restored <= kill_at
    assert restored % 2 == 0  # a commit boundary
    # elastic telemetry series populated (acceptance)
    assert "dl4jtpu_elastic_generation" in res["elastic_series"]
    assert "dl4jtpu_elastic_remesh_total" in res["elastic_series"]
    assert "dl4jtpu_elastic_lost_hosts_total" in res["elastic_series"]
    # zero retraces in the post-re-mesh steady state: the extension fit
    # (4 more steps on the re-meshed world) added NO compiles
    c0, c1, c2 = res["compiles"]
    assert c2 == c1, (
        f"post-re-mesh steady state retraced: {c1} -> {c2}\n{s_log}")

    # reference leg: fresh single-process run, SAME committed step
    solo_out = str(tmp_path / "solo.json")
    solo = _spawn("solo", "--ckpt", ckpt, "--out", solo_out,
                  "--steps", steps, "--restore-step", restored)
    solo_log = _finish(solo, timeout=240)
    assert solo.returncode == 0, f"solo reference failed:\n{solo_log}"
    ref = _load(solo_out, solo_log)
    assert ref["digest"] == res["digest"], (
        "survivor's post-re-mesh params diverged from the "
        "single-process reference resumed from the same committed step"
        f"\n{s_log}")


@pytest.mark.slow
def test_rejoin_restores_world_and_catches_up(tmp_path):
    """Scale-out through the same code path: rank 1 dies, the survivor
    re-meshes to world=1 and keeps training (throttled so the fleet is
    still live); a re-spawned rank 1 is admitted at a commit boundary,
    the fleet re-meshes back to world=2, and BOTH ranks finish the run
    with identical params."""
    ledger = str(tmp_path / "ledger")
    ckpt = str(tmp_path / "ckpt")
    coord = f"127.0.0.1:{_free_port()}"
    outs = {0: str(tmp_path / "w0.json"), 1: str(tmp_path / "w1.json")}
    steps, kill_at = 150, 10
    common = ["elastic", "--members", "0,1", "--coord", coord,
              "--ledger", ledger, "--ckpt", ckpt, "--steps", steps,
              "--commit-every", 5, "--throttle", 0.25,
              "--done-ranks", "0,1"]
    survivor = _spawn(*common, "--rank", 0, "--out", outs[0],
                      "--kill-rank", 1, "--kill-step", kill_at)
    victim = _spawn(*common, "--rank", 1, "--out", outs[1],
                    "--kill-rank", 1, "--kill-step", kill_at)
    v_log = _finish(victim)
    assert victim.returncode == -9, f"victim not killed:\n{v_log}"

    # wait for the survivor to publish the scale-IN generation before
    # re-spawning rank 1 (the restart-before-detection interleaving is a
    # documented non-goal; the scheduler restarting a host after the
    # fleet noticed is the realistic ordering)
    deadline = time.monotonic() + 120
    while not os.path.exists(os.path.join(ledger, "gen_1.json")):
        assert time.monotonic() < deadline, "scale-in never published"
        assert survivor.poll() is None, \
            f"survivor died early:\n{_finish(survivor)}"
        time.sleep(0.25)

    rejoiner = _spawn(*common, "--rank", 1, "--out", outs[1])
    r_log = _finish(rejoiner)
    s_log = _finish(survivor)
    assert rejoiner.returncode == 0, f"rejoiner failed:\n{r_log}"
    assert survivor.returncode == 0, f"survivor failed:\n{s_log}"

    s = _load(outs[0], s_log)
    r = _load(outs[1], r_log)
    # survivor: scale-in then scale-out = 2 re-meshes, ending world=2
    assert s["health"]["remeshes"] == 2, s_log
    assert s["health"]["generation"] == 2
    assert s["health"]["world"] == 2
    assert s["health"]["members"] == [0, 1]
    # rejoiner: admitted into generation 2 as process 1, caught up from
    # a committed step, finished every step
    assert r["health"]["generation"] == 2
    assert r["health"]["process_id"] == 1
    assert r["restored_step"] is not None and r["restored_step"] > 0
    assert s["iteration"] == steps and r["iteration"] == steps
    # both ranks computed the same SPMD program: identical params
    assert s["digest"] == r["digest"], (
        f"ranks disagree after rejoin\n--- survivor\n{s_log}\n"
        f"--- rejoiner\n{r_log}")
