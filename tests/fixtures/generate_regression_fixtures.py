"""Generate the serialized-format regression fixtures committed under
tests/fixtures/ (run from the repo root: python
tests/fixtures/generate_regression_fixtures.py).

Mirrors the reference's regressiontest suites
(deeplearning4j-core/src/test/java/org/deeplearning4j/regressiontest/
RegressionTest080.java et al.): models serialized by an OLD build are
committed, and every later build must keep loading them bit-exactly.
Regenerating the fixtures is an explicit format break — don't do it
casually.
"""

import os
import sys

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(ROOT, "..", ".."))

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: E402
    ElementWiseVertex, MergeVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: E402
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.updater import Adam, Nesterovs  # noqa: E402
from deeplearning4j_tpu.util.model_serializer import write_model  # noqa: E402


def mln():
    conf = (NeuralNetConfiguration.Builder()
            .seed(101)
            .updater(Adam(0.001))
            .list()
            .layer(L.ConvolutionLayer(n_out=4, kernel=(3, 3),
                                      convolution_mode="same",
                                      activation="relu"))
            .layer(L.BatchNormalization())
            .layer(L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(L.DenseLayer(n_out=8, activation="tanh"))
            .layer(L.OutputLayer(n_out=3, activation="softmax",
                                 loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(8, 8, 2))
            .build())
    net = MultiLayerNetwork(conf).init()
    # a couple of train steps so updater state + BN running stats are
    # non-trivial in the fixture
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4, 2, 8, 8)).astype(np.float32)
    y = np.zeros((4, 3), np.float32)
    y[np.arange(4), rng.integers(0, 3, 4)] = 1.0
    net.fit(x, y, epochs=2, batch_size=4)
    return net, x


def cg():
    conf = (NeuralNetConfiguration.Builder()
            .seed(202)
            .updater(Nesterovs(0.01, momentum=0.9))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(5, 7))
            .add_layer("lstm", L.GravesLSTM(n_out=6, activation="tanh"), "in")
            .add_layer("lstm2", L.LSTM(n_out=6, activation="tanh"), "in")
            .add_vertex("add", ElementWiseVertex(op="add"), "lstm", "lstm2")
            .add_vertex("mrg", MergeVertex(), "add", "lstm")
            .add_layer("out", L.RnnOutputLayer(n_out=4, loss="mcxent",
                                               activation="softmax"), "mrg")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(9)
    x = rng.standard_normal((3, 5, 7)).astype(np.float32)
    return net, x


def params_sha256(params) -> str:
    """Deterministic digest over the param pytree (sorted path order,
    float32 little-endian bytes) — pins the decode path bit-exactly."""
    import hashlib
    h = hashlib.sha256()

    def walk(tree, path):
        if isinstance(tree, dict):
            for k in sorted(tree):
                walk(tree[k], path + "/" + str(k))
        elif tree is not None and hasattr(tree, "shape"):
            h.update(path.encode())
            h.update(np.ascontiguousarray(
                np.asarray(tree, np.float32)).tobytes())

    walk(params, "")
    return h.hexdigest()


def main():
    import json

    net, x = mln()
    write_model(net, os.path.join(ROOT, "regression_mln_v1.zip"))
    np.save(os.path.join(ROOT, "regression_mln_v1_input.npy"), x)
    np.save(os.path.join(ROOT, "regression_mln_v1_output.npy"),
            np.asarray(net.output(x)))
    with open(os.path.join(ROOT, "regression_mln_v1.json"), "w") as f:
        f.write(net.conf.to_json())

    g, xg = cg()
    write_model(g, os.path.join(ROOT, "regression_cg_v1.zip"))
    np.save(os.path.join(ROOT, "regression_cg_v1_input.npy"), xg)
    np.save(os.path.join(ROOT, "regression_cg_v1_output.npy"),
            np.asarray(g.output(xg)[0]))
    with open(os.path.join(ROOT, "regression_cg_v1.json"), "w") as f:
        f.write(g.conf.to_json())

    with open(os.path.join(ROOT, "regression_checksums.json"), "w") as f:
        json.dump({"mln_v1_params": params_sha256(net.params),
                   "cg_v1_params": params_sha256(g.params)}, f, indent=2)
    print("fixtures written to", ROOT)


if __name__ == "__main__":
    main()
