"""Generate the serialized-format regression fixtures committed under
tests/fixtures/ (run from the repo root: python
tests/fixtures/generate_regression_fixtures.py).

Mirrors the reference's regressiontest suites
(deeplearning4j-core/src/test/java/org/deeplearning4j/regressiontest/
RegressionTest080.java et al.): models serialized by an OLD build are
committed, and every later build must keep loading them bit-exactly.
Regenerating the fixtures is an explicit format break — don't do it
casually.
"""

import os
import sys

import numpy as np

# this environment preloads a TPU plugin and sets JAX_PLATFORMS before
# Python starts, so the env var is too late — switch via jax.config (the
# tests/conftest.py gotcha); fixtures are generated on CPU
import jax

jax.config.update("jax_platforms", "cpu")

ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(ROOT, "..", ".."))

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: E402
    ElementWiseVertex, MergeVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: E402
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.updater import Adam, Nesterovs  # noqa: E402
from deeplearning4j_tpu.util.model_serializer import write_model  # noqa: E402


def mln():
    conf = (NeuralNetConfiguration.Builder()
            .seed(101)
            .updater(Adam(0.001))
            .list()
            .layer(L.ConvolutionLayer(n_out=4, kernel=(3, 3),
                                      convolution_mode="same",
                                      activation="relu"))
            .layer(L.BatchNormalization())
            .layer(L.SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(L.DenseLayer(n_out=8, activation="tanh"))
            .layer(L.OutputLayer(n_out=3, activation="softmax",
                                 loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(8, 8, 2))
            .build())
    net = MultiLayerNetwork(conf).init()
    # a couple of train steps so updater state + BN running stats are
    # non-trivial in the fixture
    rng = np.random.default_rng(7)
    x = rng.standard_normal((4, 2, 8, 8)).astype(np.float32)
    y = np.zeros((4, 3), np.float32)
    y[np.arange(4), rng.integers(0, 3, 4)] = 1.0
    net.fit(x, y, epochs=2, batch_size=4)
    return net, x


def cg():
    conf = (NeuralNetConfiguration.Builder()
            .seed(202)
            .updater(Nesterovs(0.01, momentum=0.9))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(5, 7))
            .add_layer("lstm", L.GravesLSTM(n_out=6, activation="tanh"), "in")
            .add_layer("lstm2", L.LSTM(n_out=6, activation="tanh"), "in")
            .add_vertex("add", ElementWiseVertex(op="add"), "lstm", "lstm2")
            .add_vertex("mrg", MergeVertex(), "add", "lstm")
            .add_layer("out", L.RnnOutputLayer(n_out=4, loss="mcxent",
                                               activation="softmax"), "mrg")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(9)
    x = rng.standard_normal((3, 5, 7)).astype(np.float32)
    return net, x


def tfm():
    """Transformer stack fixture (v1, added later than mln/cg): pins the
    SelfAttentionLayer / LayerNormalization / PositionalEmbeddingLayer
    serde + checkpoint formats."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.zoo import TextGenerationTransformer

    model = TextGenerationTransformer(vocab_size=12, seed=303, embed_dim=16,
                                      n_heads=2, n_layers=2, max_length=10,
                                      updater=Adam(0.001))
    net = model.init()
    rng = np.random.default_rng(11)
    ids = rng.integers(0, 12, (2, 10))
    x = np.zeros((2, 12, 10), np.float32)
    x[np.arange(2)[:, None], ids, np.arange(10)[None, :]] = 1.0
    y = np.roll(x, -1, axis=2)
    net.fit(DataSet(x, y))   # non-trivial updater state in the fixture
    return net, x


def params_sha256(params) -> str:
    """Deterministic digest over the param pytree (sorted path order,
    float32 little-endian bytes) — pins the decode path bit-exactly."""
    import hashlib
    h = hashlib.sha256()

    def walk(tree, path):
        if isinstance(tree, dict):
            for k in sorted(tree):
                walk(tree[k], path + "/" + str(k))
        elif tree is not None and hasattr(tree, "shape"):
            h.update(path.encode())
            h.update(np.ascontiguousarray(
                np.asarray(tree, np.float32)).tobytes())

    walk(params, "")
    return h.hexdigest()


def main(which=("mln", "cg", "tfm")):
    import json

    try:
        with open(os.path.join(ROOT, "regression_checksums.json")) as f:
            sums = json.load(f)
    except FileNotFoundError:
        sums = {}

    unknown = set(which) - {"mln", "cg", "tfm"}
    if unknown:
        sys.exit(f"unknown fixture name(s): {sorted(unknown)} "
                 "(choose from mln, cg, tfm)")

    if "mln" in which:
        net, x = mln()
        write_model(net, os.path.join(ROOT, "regression_mln_v1.zip"))
        np.save(os.path.join(ROOT, "regression_mln_v1_input.npy"), x)
        np.save(os.path.join(ROOT, "regression_mln_v1_output.npy"),
                np.asarray(net.output(x)))
        with open(os.path.join(ROOT, "regression_mln_v1.json"), "w") as f:
            f.write(net.conf.to_json())
        sums["mln_v1_params"] = params_sha256(net.params)

    def write_graph_fixture(name, builder):
        g, xg = builder()
        write_model(g, os.path.join(ROOT, f"regression_{name}_v1.zip"))
        np.save(os.path.join(ROOT, f"regression_{name}_v1_input.npy"), xg)
        out = g.output(xg)
        np.save(os.path.join(ROOT, f"regression_{name}_v1_output.npy"),
                np.asarray(out[0] if isinstance(out, (list, tuple))
                           else out))
        with open(os.path.join(ROOT, f"regression_{name}_v1.json"),
                  "w") as f:
            f.write(g.conf.to_json())
        sums[f"{name}_v1_params"] = params_sha256(g.params)

    for name, builder in (("cg", cg), ("tfm", tfm)):
        if name in which:
            write_graph_fixture(name, builder)

    with open(os.path.join(ROOT, "regression_checksums.json"), "w") as f:
        json.dump(sums, f, indent=2)
    print("fixtures written to", ROOT, "(", ", ".join(which), ")")


if __name__ == "__main__":
    main(tuple(sys.argv[1:]) or ("mln", "cg", "tfm"))
