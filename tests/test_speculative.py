"""Speculative decoding (util/decoding.speculative_sample) and the
stream-state rewind primitive it builds on."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers import rewind_stream_state
from deeplearning4j_tpu.util import decoding
from deeplearning4j_tpu.zoo import TextGenerationLSTM, TextGenerationTransformer

RNG = np.random.default_rng(0)


def _tfm(layers=1, embed=16, seed=12345, window=None, cache=32):
    return TextGenerationTransformer(vocab_size=12, embed_dim=embed,
                                     n_heads=2, n_layers=layers,
                                     max_length=cache, window=window,
                                     seed=seed)


def _one_hot(seq, vocab=12):
    h = np.zeros((1, vocab, len(seq)), np.float32)
    h[0, list(seq), np.arange(len(seq))] = 1.0
    return h


class TestRewind:
    def test_rewind_equals_never_fed(self):
        """Feed 3 tokens, rewind 2, re-feed different ones: outputs equal
        a stream that never saw the rejected tokens."""
        model = _tfm()
        a, b = model.init(), model.init()
        a.rnn_time_step(_one_hot([1, 2, 3]))
        out_a = np.asarray(a.rnn_time_step(_one_hot([4, 5, 6])))
        rewind_stream_state(a, 2)
        got = np.asarray(a.rnn_time_step(_one_hot([7, 8])))

        b.rnn_time_step(_one_hot([1, 2, 3]))
        b.rnn_time_step(_one_hot([4]))
        want = np.asarray(b.rnn_time_step(_one_hot([7, 8])))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_rewind_rolling_window(self):
        model = _tfm(window=4, cache=16)
        a, b = model.init(), model.init()
        a.rnn_time_step(_one_hot([1, 2, 3, 4, 5]))
        a.rnn_time_step(_one_hot([6, 7, 8]))
        rewind_stream_state(a, 3)
        got = np.asarray(a.rnn_time_step(_one_hot([9, 10])))
        b.rnn_time_step(_one_hot([1, 2, 3, 4, 5]))
        want = np.asarray(b.rnn_time_step(_one_hot([9, 10])))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_rewind_rolling_needs_headroom(self):
        model = _tfm(window=4, cache=5)
        net = model.init()
        net.rnn_time_step(_one_hot([1, 2, 3]))
        with pytest.raises(ValueError, match="cache_length >= window"):
            rewind_stream_state(net, 2)

    def test_lstm_state_rejected(self):
        model = TextGenerationLSTM(vocab_size=10, hidden=8, layers=1,
                                   max_length=20)
        net = model.init()
        net.rnn_time_step(_one_hot([1, 2], 10))
        with pytest.raises(ValueError, match="h/c"):
            rewind_stream_state(net, 1)

    def test_budget_counter_rewinds(self):
        model = _tfm(cache=8)
        net = model.init()
        net.rnn_time_step(_one_hot([1, 2, 3, 4, 5, 6]))
        rewind_stream_state(net, 4)
        # 2 + 6 would exceed the 8 capacity without the rewind
        net.rnn_time_step(_one_hot([1, 2, 3, 4, 5, 6]))


class TestSpeculativeSample:
    def test_greedy_identical_to_regular(self):
        """top_k=1: speculative output is bit-identical to plain greedy
        decoding, for an UNRELATED draft model and any gamma."""
        target = _tfm(layers=2, embed=32, seed=1)
        draft = _tfm(layers=1, embed=16, seed=999)   # different model
        tnet, dnet = target.init(), draft.init()
        want = target.sample_stream(tnet, [1, 2, 3], steps=9, top_k=1,
                                    rng=np.random.default_rng(0))
        for gamma in (1, 3, 5):
            got = decoding.speculative_sample(
                tnet, dnet, [1, 2, 3], steps=9, vocab_size=12,
                gamma=gamma, top_k=1, rng=np.random.default_rng(0))
            assert got == want, f"gamma={gamma}"

    def test_draft_equals_target_always_accepts(self):
        """Identical draft == always-accept: gamma+1 tokens per target
        dispatch (count the verify forwards)."""
        target = _tfm(layers=1, embed=16, seed=7, cache=64)
        tnet, dnet = target.init(), target.init()
        calls = {"n": 0}
        orig = type(tnet).rnn_time_step

        def counting(self, *a, **k):
            if self is tnet:
                calls["n"] += 1
            return orig(self, *a, **k)

        type(tnet).rnn_time_step = counting
        try:
            out = decoding.speculative_sample(
                tnet, dnet, [1, 2, 3], steps=12, vocab_size=12,
                gamma=3, top_k=1, rng=np.random.default_rng(1))
        finally:
            type(tnet).rnn_time_step = orig
        assert len(out) == 15
        # identical models + greedy => every proposal accepted: 12 new
        # tokens in 3 rounds of gamma+1, the committed token riding each
        # next verify => 2 prime chunks (3 = 2+1) + 3 verify forwards.
        # Plain decode would need 2 + 12 = 14 target calls.
        assert calls["n"] == 5, calls["n"]

    def test_sampled_mode_runs_and_is_deterministic(self):
        target = _tfm(layers=1, embed=32, seed=3)
        draft = _tfm(layers=1, embed=16, seed=4)
        tnet, dnet = target.init(), draft.init()
        a = decoding.speculative_sample(tnet, dnet, [1, 2], steps=8,
                                        vocab_size=12, gamma=4,
                                        temperature=0.8,
                                        rng=np.random.default_rng(5))
        b = decoding.speculative_sample(tnet, dnet, [1, 2], steps=8,
                                        vocab_size=12, gamma=4,
                                        temperature=0.8,
                                        rng=np.random.default_rng(5))
        assert a == b
        assert len(a) == 10 and all(0 <= t < 12 for t in a)

    def test_zoo_wrapper(self):
        target = _tfm(layers=1, embed=32, seed=3)
        draft = _tfm(layers=1, embed=16, seed=4)
        tnet, dnet = target.init(), draft.init()
        out = target.speculative_sample(tnet, dnet, [1, 2, 3], steps=6,
                                        gamma=2, top_k=1)
        want = target.sample_stream(tnet, [1, 2, 3], steps=6, top_k=1)
        assert out == want

    def test_respects_max_length(self):
        target = _tfm(cache=8)
        draft = _tfm(seed=9, cache=8)
        tnet, dnet = target.init(), draft.init()
        out = decoding.speculative_sample(tnet, dnet, [1, 2, 3], steps=50,
                                          vocab_size=12, gamma=4,
                                          max_length=8, top_k=1,
                                          rng=np.random.default_rng(2))
        assert len(out) == 8

    def test_gamma_validated(self):
        target = _tfm()
        tnet = target.init()
        with pytest.raises(ValueError, match="gamma"):
            decoding.speculative_sample(tnet, tnet, [1], steps=2,
                                        vocab_size=12, gamma=0)

    def test_lstm_target_fails_fast(self):
        """A non-rewindable target errors at ENTRY, before any forward
        (not mid-generation at the first rejection)."""
        lstm = TextGenerationLSTM(vocab_size=10, hidden=8, layers=1,
                                  max_length=20)
        lnet = lstm.init()
        with pytest.raises(ValueError, match="h/c"):
            decoding.speculative_sample(
                lnet, decoding.prompt_lookup_proposer(), [1, 2], steps=4,
                vocab_size=10)

    def test_rolling_without_headroom_fails_fast(self):
        target = _tfm(window=4, cache=5)
        tnet = target.init()
        with pytest.raises(ValueError, match="cache_length >= window"):
            decoding.speculative_sample(
                tnet, decoding.prompt_lookup_proposer(), [1, 2], steps=4,
                vocab_size=12, gamma=4)


class TestPromptLookup:
    def test_proposer_finds_continuation(self):
        propose = decoding.prompt_lookup_proposer(ngram=2)
        ids = [5, 6, 7, 8, 9, 5, 6]
        assert propose(ids, 3) == [7, 8, 9]     # continues the 5,6 match
        assert propose(ids, 1) == [7]
        assert propose([1, 2, 3], 4) == []      # no earlier match
        assert propose([1], 4) == []            # too short

    def test_proposer_prefers_most_recent_match(self):
        propose = decoding.prompt_lookup_proposer(ngram=2)
        ids = [1, 2, 3, 1, 2, 4, 1, 2]
        assert propose(ids, 2) == [4, 1]        # latest (1,2) -> 4

    def test_greedy_identical_with_prompt_lookup_draft(self):
        """Draft-free speculation preserves greedy decoding exactly, on
        a repetitive prompt where proposals actually fire."""
        target = _tfm(layers=2, embed=32, seed=1, cache=64)
        tnet = target.init()
        prompt = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
        want = target.sample_stream(tnet, prompt, steps=10, top_k=1,
                                    rng=np.random.default_rng(0))
        got = decoding.speculative_sample(
            tnet, decoding.prompt_lookup_proposer(ngram=2), prompt,
            steps=10, vocab_size=12, gamma=4, top_k=1,
            rng=np.random.default_rng(0))
        assert got == want

    def test_empty_proposals_degrade_to_plain_decoding(self):
        """A prompt with no repeats: every round falls back to a plain
        single-token step; output still matches greedy decoding."""
        target = _tfm(layers=1, embed=16, seed=2, cache=64)
        tnet = target.init()
        propose_nothing = lambda ids, gamma: []
        want = target.sample_stream(tnet, [1, 2, 3], steps=6, top_k=1,
                                    rng=np.random.default_rng(0))
        got = decoding.speculative_sample(
            tnet, propose_nothing, [1, 2, 3], steps=6, vocab_size=12,
            gamma=4, top_k=1, rng=np.random.default_rng(0))
        assert got == want

    def test_padded_prime_composes(self):
        """prime_padded=True (single-dispatch left-padded priming)
        inside speculation matches the chunked-priming run exactly."""
        target = _tfm(layers=1, embed=32, seed=3)
        tnet = target.init()
        prompt = [1, 2, 3, 4, 5]
        a = decoding.speculative_sample(
            tnet, decoding.prompt_lookup_proposer(2), prompt, steps=8,
            vocab_size=12, gamma=3, top_k=1,
            rng=np.random.default_rng(0))
        b = decoding.speculative_sample(
            tnet, decoding.prompt_lookup_proposer(2), prompt, steps=8,
            vocab_size=12, gamma=3, top_k=1, prime_padded=True,
            rng=np.random.default_rng(0))
        assert a == b

    def test_quantized_draft_composes(self):
        """The serving features compose: an int8-quantized draft model
        proposes, the fp target verifies — greedy output still exactly
        matches plain decoding."""
        from deeplearning4j_tpu.optimize import quantize_for_inference
        target = _tfm(layers=2, embed=32, seed=1)
        draft = _tfm(layers=1, embed=16, seed=99)
        tnet = target.init()
        dnet = quantize_for_inference(draft.init(), min_size=256)
        want = target.sample_stream(tnet, [1, 2, 3], steps=8, top_k=1)
        got = decoding.speculative_sample(tnet, dnet, [1, 2, 3], steps=8,
                                          vocab_size=12, gamma=3,
                                          top_k=1,
                                          rng=np.random.default_rng(0))
        assert got == want

    def test_bad_draft_rejected(self):
        target = _tfm()
        tnet = target.init()
        with pytest.raises(TypeError, match="draft"):
            decoding.speculative_sample(tnet, object(), [1, 2], steps=2,
                                        vocab_size=12)
