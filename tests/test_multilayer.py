"""MultiLayerNetwork end-to-end tests (ref test model: deeplearning4j-core
nn/multilayer/: MultiLayerTest, BackPropMLPTest, MultiLayerTestRNN,
TestVariableLengthTS)."""

import os
import tempfile

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener,
    ScoreIterationListener,
)

RNG = np.random.default_rng(7)


def xor_data(n=200):
    x = RNG.random((n, 2)).astype(np.float32)
    y_bit = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(int)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), y_bit] = 1.0
    return x, y


def mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.01))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.feed_forward(2))
            .build())
    return MultiLayerNetwork(conf).init()


class TestTraining:
    def test_xor_converges(self):
        x, y = xor_data(400)
        net = mlp()
        collector = CollectScoresIterationListener()
        net.set_listeners(collector)
        net.fit(x, y, epochs=60, batch_size=64)
        e = net.evaluate(DataSet(x, y))
        assert e.accuracy() > 0.9, e.stats()
        # score decreased
        first = collector.scores[0][1]
        last = collector.scores[-1][1]
        assert last < first * 0.5

    def test_updaters_all_step(self):
        from deeplearning4j_tpu.nn.updater import (AdaDelta, AdaGrad, AdaMax,
                                                   Nadam, RmsProp)
        x, y = xor_data(64)
        for upd in (Sgd(0.1), Nesterovs(0.1, momentum=0.9), Adam(0.01),
                    AdaMax(0.01), Nadam(0.01), RmsProp(0.01), AdaGrad(0.05),
                    AdaDelta()):
            net = mlp(updater=upd)
            s0 = net.score(DataSet(x, y))
            net.fit(x, y, epochs=5, batch_size=32)
            s1 = net.score(DataSet(x, y))
            assert np.isfinite(s1), type(upd).__name__
            assert s1 < s0 * 1.5, f"{type(upd).__name__} diverged: {s0} -> {s1}"

    def test_deterministic_with_seed(self):
        x, y = xor_data(64)
        n1, n2 = mlp(seed=99), mlp(seed=99)
        n1.fit(x, y, epochs=3, batch_size=32)
        n2.fit(x, y, epochs=3, batch_size=32)
        for k in n1.params:
            for pk in n1.params[k]:
                np.testing.assert_array_equal(np.asarray(n1.params[k][pk]),
                                              np.asarray(n2.params[k][pk]))

    def test_batchnorm_training(self):
        x, y = xor_data(256)
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(0.01)).list()
                .layer(DenseLayer(n_out=16, activation="identity"))
                .layer(BatchNormalization())
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=2, loss="mcxent", activation="softmax"))
                .set_input_type(InputType.feed_forward(2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y, epochs=30, batch_size=64)
        # running stats were updated
        assert not np.allclose(np.asarray(net.state["1"]["mean"]), 0.0)
        assert net.evaluate(DataSet(x, y)).accuracy() > 0.85


class TestRnnTraining:
    def test_sequence_classification(self):
        # classify by sign of sum over sequence
        n, f, t = 128, 3, 6
        x = RNG.standard_normal((n, f, t)).astype(np.float32)
        s = x.sum(axis=(1, 2))
        y = np.zeros((n, 2, t), np.float32)
        y[s > 0, 1, :] = 1.0
        y[s <= 0, 0, :] = 1.0
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(0.02)).list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=2, loss="mcxent", activation="softmax"))
                .set_input_type(InputType.recurrent(f, t))
                .build())
        net = MultiLayerNetwork(conf).init()
        s0 = net.score(DataSet(x, y))
        net.fit(x, y, epochs=20, batch_size=32)
        assert net.score(DataSet(x, y)) < s0 * 0.7

    def test_tbptt_runs(self):
        n, f, t = 16, 2, 12
        x = RNG.standard_normal((n, f, t)).astype(np.float32)
        y = RNG.standard_normal((n, 2, t)).astype(np.float32)
        conf = (NeuralNetConfiguration.Builder()
                .seed(5).updater(Sgd(0.01)).list()
                .layer(LSTM(n_out=4))
                .layer(RnnOutputLayer(n_out=2, loss="mse", activation="identity"))
                .set_input_type(InputType.recurrent(f, t))
                .tbptt(4)
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y, epochs=2, batch_size=8)
        assert np.isfinite(net.score_value)

    def test_rnn_time_step_streaming(self):
        """Streaming rnn_time_step must equal the full-sequence forward
        (ref: MultiLayerTestRNN#testRnnTimeStep)."""
        f, t = 3, 5
        conf = (NeuralNetConfiguration.Builder()
                .seed(11).updater(Sgd(0.1)).list()
                .layer(LSTM(n_out=4))
                .layer(RnnOutputLayer(n_out=2, loss="mse", activation="identity"))
                .set_input_type(InputType.recurrent(f, t))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.standard_normal((2, f, t)).astype(np.float32)
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        steps = []
        for s in range(t):
            out = net.rnn_time_step(x[:, :, s:s + 1])
            steps.append(np.asarray(out))
        streamed = np.concatenate(steps, axis=2)
        np.testing.assert_allclose(streamed, full, rtol=1e-4, atol=1e-5)


class TestPersistence:
    def test_save_restore_roundtrip(self):
        from deeplearning4j_tpu.util.model_serializer import (
            restore_multi_layer_network, write_model)
        x, y = xor_data(64)
        net = mlp()
        net.fit(x, y, epochs=3, batch_size=32)
        out_before = np.asarray(net.output(x[:8]))
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "model.zip")
            write_model(net, path)
            net2 = restore_multi_layer_network(path)
        out_after = np.asarray(net2.output(x[:8]))
        np.testing.assert_allclose(out_before, out_after, rtol=1e-6)
        assert net2.iteration_count == net.iteration_count
        # training can continue (updater state restored)
        net2.fit(x, y, epochs=1, batch_size=32)

    def test_summary(self):
        net = mlp()
        s = net.summary()
        assert "DenseLayer" in s and "Total params" in s
        assert net.num_params() == 2 * 16 + 16 + 16 * 16 + 16 + 16 * 2 + 2


class TestComputationGraphRnnTimeStep:
    def test_streaming_matches_full_sequence(self):
        """CG rnn_time_step over split chunks == one full-sequence output
        (ref: ComputationGraph rnnTimeStep semantics)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (ComputationGraphConfiguration.GraphBuilder()
                .add_inputs("in")
                .add_layer("lstm", LSTM(n_out=6), "in")
                .add_layer("out", RnnOutputLayer(n_out=2, loss="mcxent",
                                                 activation="softmax"),
                           "lstm")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(3, 8))
                .build())
        net = ComputationGraph(conf).init()
        x = RNG.standard_normal((2, 3, 8)).astype(np.float32)
        full = np.asarray(net.output(x))

        net.rnn_clear_previous_state()
        o1 = np.asarray(net.rnn_time_step(x[:, :, :5]))
        o2 = np.asarray(net.rnn_time_step(x[:, :, 5:]))
        stream = np.concatenate([o1, o2], axis=-1)
        np.testing.assert_allclose(stream, full, atol=1e-5, rtol=1e-5)

        # clearing state resets the stream
        net.rnn_clear_previous_state()
        o1b = np.asarray(net.rnn_time_step(x[:, :, :5]))
        np.testing.assert_allclose(o1b, o1, atol=1e-6)


class TestComputationGraphMultiOutput:
    def test_single_forward_updates_bn_state_once(self):
        """A 2-output CG with BatchNormalization in the shared trunk must run
        ONE forward per train step (ref: ComputationGraph
        computeGradientAndScore :1298) — the BN running mean after one step
        equals exactly one EMA update, not one per output layer."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                       DenseLayer, OutputLayer)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.datasets.dataset import DataSet

        conf = (ComputationGraphConfiguration.GraphBuilder()
                .add_inputs("in")
                .add_layer("trunk", DenseLayer(n_out=5, activation="identity"),
                           "in")
                .add_layer("bn", BatchNormalization(), "trunk")
                .add_layer("outA", OutputLayer(n_out=2, loss="mcxent",
                                               activation="softmax"), "bn")
                .add_layer("outB", OutputLayer(n_out=3, loss="mcxent",
                                               activation="softmax"), "bn")
                .set_outputs("outA", "outB")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()

        x = RNG.standard_normal((16, 4)).astype(np.float32)
        ya = np.zeros((16, 2), np.float32); ya[:, 0] = 1
        yb = np.zeros((16, 3), np.float32); yb[:, 1] = 1

        # expected single-EMA update of the running mean from zeros
        trunk_out = x @ np.asarray(net.params["trunk"]["W"]) + \
            np.asarray(net.params["trunk"]["b"])
        decay = conf.vertices["bn"].layer.decay
        want_mean = (1.0 - decay) * trunk_out.mean(axis=0)

        net._fit_batch(DataSet({"in": x}, {"outA": ya, "outB": yb}))
        got_mean = np.asarray(net.state["bn"]["mean"])
        np.testing.assert_allclose(got_mean, want_mean, rtol=1e-4, atol=1e-6)

    def test_multi_output_losses_sum(self):
        from deeplearning4j_tpu.nn.conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.datasets.dataset import DataSet

        conf = (ComputationGraphConfiguration.GraphBuilder()
                .add_inputs("in")
                .add_layer("trunk", DenseLayer(n_out=6), "in")
                .add_layer("outA", OutputLayer(n_out=2, loss="mcxent",
                                               activation="softmax"), "trunk")
                .add_layer("outB", OutputLayer(n_out=2, loss="mse",
                                               activation="identity"), "trunk")
                .set_outputs("outA", "outB")
                .set_input_types(InputType.feed_forward(3))
                .build())
        net = ComputationGraph(conf).init()
        x = RNG.standard_normal((8, 3)).astype(np.float32)
        ya = np.zeros((8, 2), np.float32); ya[:, 0] = 1
        yb = RNG.standard_normal((8, 2)).astype(np.float32)
        before = None
        for _ in range(30):
            net._fit_batch(DataSet({"in": x}, {"outA": ya, "outB": yb}))
            if before is None:
                before = net.score_value
        assert net.score_value < before
        outs = net.output({"in": x})
        assert outs[0].shape == (8, 2) and outs[1].shape == (8, 2)
