"""DL4J checkpoint (zip) importer tests.

Mirrors the reference's checkpoint-equivalence role of
util/ModelSerializer.java:90-137 round-trips and the regressiontest/ suites:
hand-written flat vectors laid out per the reference param initializers must
import to networks whose output() matches independent numpy math in DL4J's
own semantics (IFOG block ordering, peephole columns, 'f'-order views)."""

import json
import os
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import dl4j as d4
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    RBM,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

RNG = np.random.default_rng(31)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


class TestNd4jCodec:
    def test_roundtrip_row_vector(self):
        arr = RNG.standard_normal(37).astype(np.float32)
        out = d4.read_nd4j_array(d4.write_nd4j_array(arr))
        assert out.shape == (1, 37)
        np.testing.assert_allclose(out.ravel(), arr, rtol=1e-6)

    def test_roundtrip_matrix_and_double(self):
        arr = RNG.standard_normal((3, 5))
        out = d4.read_nd4j_array(d4.write_nd4j_array(arr, "DOUBLE"))
        np.testing.assert_allclose(out, arr)

    def test_big_endian_on_wire(self):
        # java DataOutputStream is big-endian; spot-check a known value
        data = d4.write_nd4j_array(np.array([1.0], np.float32))
        assert b"\x3f\x80\x00\x00" in data  # 1.0f big-endian


class TestHandWrittenFlatVector:
    """VERDICT r1 acceptance: construct a known MLN config, hand-write its
    flat vector per DefaultParamInitializer view layout, import, and match
    output() exactly."""

    def test_dense_output_mlp(self):
        n_in, n_hid, n_out = 2, 3, 2
        w1 = RNG.standard_normal((n_in, n_hid))
        b1 = RNG.standard_normal(n_hid)
        w2 = RNG.standard_normal((n_hid, n_out))
        b2 = RNG.standard_normal(n_out)
        # DL4J flat view: per layer W ('f' order) then b
        flat = np.concatenate([w1.ravel(order="F"), b1,
                               w2.ravel(order="F"), b2]).astype(np.float32)

        conf_json = json.dumps({
            "backprop": True,
            "confs": [
                {"seed": 12, "layer": {"dense": {
                    "nin": n_in, "nout": n_hid,
                    "activationFn": {"TanH": {}}}}},
                {"seed": 12, "layer": {"output": {
                    "nin": n_hid, "nout": n_out,
                    "activationFn": {"Softmax": {}},
                    "lossFn": {"LossMCXENT": {}}}}},
            ],
        })

        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.zip")
            import zipfile
            with zipfile.ZipFile(path, "w") as zf:
                zf.writestr("configuration.json", conf_json)
                zf.writestr("coefficients.bin", d4.write_nd4j_array(flat))
            net = d4.restore_multi_layer_network(path)

        x = RNG.standard_normal((4, n_in)).astype(np.float32)
        got = np.asarray(net.output(x))

        h = np.tanh(x @ w1 + b1)
        z = h @ w2 + b2
        want = np.exp(z - z.max(1, keepdims=True))
        want /= want.sum(1, keepdims=True)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_conv_bias_first_layout(self):
        """ConvolutionParamInitializer stores bias BEFORE the c-order
        [nOut, nIn, kH, kW] kernel (ConvolutionParamInitializer.java:118)."""
        n_out = 2
        w = RNG.standard_normal((n_out, 1, 2, 2))
        b = RNG.standard_normal(n_out)
        wd = RNG.standard_normal((2 * 3 * 3, 2))
        bd = RNG.standard_normal(2)
        flat = np.concatenate([b, w.ravel(order="C"),
                               wd.ravel(order="F"), bd]).astype(np.float32)
        conf_json = json.dumps({
            "backprop": True,
            "confs": [
                {"layer": {"convolution": {
                    "nin": 1, "nout": n_out, "kernelSize": [2, 2],
                    "stride": [1, 1], "padding": [0, 0],
                    "activationFn": {"Identity": {}}}}},
                {"layer": {"output": {
                    "nin": 18, "nout": 2,
                    "activationFn": {"Identity": {}},
                    "lossFn": {"LossMSE": {}}}}},
            ],
        })
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.zip")
            import zipfile
            with zipfile.ZipFile(path, "w") as zf:
                zf.writestr("configuration.json", conf_json)
                zf.writestr("coefficients.bin", d4.write_nd4j_array(flat))
            # conv-first: spatial dims aren't in the DL4J config; pin them
            net = d4.restore_multi_layer_network(
                path, input_type=InputType.convolutional(4, 4, 1))

        x = RNG.standard_normal((2, 1, 4, 4)).astype(np.float32)
        got = np.asarray(net.output(x))

        # manual valid conv 2x2 stride 1 + flatten (DL4J flattens NCHW
        # c-order) + dense
        N = x.shape[0]
        conv = np.zeros((N, n_out, 3, 3))
        for n in range(N):
            for o in range(n_out):
                for i_ in range(3):
                    for j in range(3):
                        conv[n, o, i_, j] = np.sum(
                            x[n, 0, i_:i_ + 2, j:j + 2] * w[o, 0]) + b[o]
        h = conv.reshape(N, -1)
        want = h @ wd + bd
        np.testing.assert_allclose(got, want, atol=1e-4)


class TestLSTMGateOrder:
    """DL4J's IFOG blocks [i(tanh candidate), f, o, g(sigmoid input gate)]
    (LSTMHelpers.java:214-305) must map onto our (i,f,c,o) kernel so that
    the imported network reproduces DL4J's recurrence exactly."""

    def _dl4j_lstm_numpy(self, x_tc, W, RW, b, peep=None):
        """Reference-semantics LSTM in numpy. x_tc: [T, nIn]; W [nIn,4H]
        IFOG; RW [H, 4H(+3)]; b [4H]. Returns [T, H]."""
        H = RW.shape[0]
        ifog_rw = RW[:, :4 * H]
        wFF = RW[:, 4 * H] if peep else None
        wOO = RW[:, 4 * H + 1] if peep else None
        wGG = RW[:, 4 * H + 2] if peep else None
        h = np.zeros(H)
        c = np.zeros(H)
        out = []
        for t in range(x_tc.shape[0]):
            z = x_tc[t] @ W + h @ ifog_rw + b
            zi, zf, zo, zg = z[:H], z[H:2 * H], z[2 * H:3 * H], z[3 * H:]
            if peep:
                zf = zf + c * wFF
                zg = zg + c * wGG
            ia = np.tanh(zi)          # "input activation" = candidate
            fa = _sigmoid(zf)
            ga = _sigmoid(zg)         # "input mod gate" = input gate
            c = fa * c + ga * ia
            if peep:
                zo = zo + c * wOO
            oa = _sigmoid(zo)
            h = oa * np.tanh(c)
            out.append(h.copy())
        return np.stack(out)

    @pytest.mark.parametrize("graves", [False, True])
    def test_imported_lstm_matches_dl4j_recurrence(self, graves):
        n_in, H, T = 3, 4, 5
        W = RNG.standard_normal((n_in, 4 * H)) * 0.4
        RW = RNG.standard_normal((H, 4 * H + (3 if graves else 0))) * 0.4
        b = RNG.standard_normal(4 * H) * 0.1
        wo = RNG.standard_normal((H, 2)) * 0.5
        bo = RNG.standard_normal(2) * 0.1
        flat = np.concatenate([
            W.ravel(order="F"), RW.ravel(order="F"), b,
            wo.ravel(order="F"), bo]).astype(np.float64)

        lname = "gravesLSTM" if graves else "LSTM"
        conf_json = json.dumps({
            "backprop": True,
            "confs": [
                {"layer": {lname: {"nin": n_in, "nout": H,
                                   "activationFn": {"TanH": {}}}}},
                {"layer": {"rnnoutput": {
                    "nin": H, "nout": 2,
                    "activationFn": {"Identity": {}},
                    "lossFn": {"LossMSE": {}}}}},
            ],
        })
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.zip")
            import zipfile
            with zipfile.ZipFile(path, "w") as zf:
                zf.writestr("configuration.json", conf_json)
                zf.writestr("coefficients.bin",
                            d4.write_nd4j_array(flat.astype(np.float32)))
            net = d4.restore_multi_layer_network(path)

        x = (RNG.standard_normal((1, n_in, T)) * 0.5).astype(np.float32)
        got = np.asarray(net.output(x))[0]  # [2, T]

        hs = self._dl4j_lstm_numpy(x[0].T, W, RW, b, peep=graves)  # [T, H]
        want = (hs @ wo + bo).T  # [2, T]
        np.testing.assert_allclose(got, want, atol=1e-4)


class TestRoundTrip:
    def test_mlp_save_restore_identical(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(5).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.standard_normal((6, 4)).astype(np.float32)
        want = np.asarray(net.output(x))
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.zip")
            d4.save_dl4j_format(net, path)
            net2 = d4.restore_multi_layer_network(path)
        got = np.asarray(net2.output(x))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_bidirectional_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.layers import GravesBidirectionalLSTM
        conf = (NeuralNetConfiguration.Builder()
                .seed(5).list()
                .layer(GravesBidirectionalLSTM(n_out=4))
                .layer(RnnOutputLayer(n_out=2, loss="mse",
                                      activation="identity"))
                .set_input_type(InputType.recurrent(3, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        # randomize peepholes so the permutation is actually exercised
        import jax.numpy as jnp
        net.params["0"]["PF"] = jnp.asarray(
            RNG.standard_normal((3, 4)), jnp.float32)
        net.params["0"]["PB"] = jnp.asarray(
            RNG.standard_normal((3, 4)), jnp.float32)
        x = RNG.standard_normal((2, 3, 6)).astype(np.float32)
        want = np.asarray(net.output(x))
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.zip")
            d4.save_dl4j_format(net, path)
            net2 = d4.restore_multi_layer_network(path)
        got = np.asarray(net2.output(x))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_flat_mapping_inverse(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).list()
                .layer(GravesLSTM(n_out=5))
                .layer(RnnOutputLayer(n_out=2, loss="mse",
                                      activation="identity"))
                .set_input_type(InputType.recurrent(3, 4))
                .build())
        net = MultiLayerNetwork(conf).init()
        flat = d4.params_to_flat(net.conf, net.params, net.state)
        params, _ = d4.params_from_flat(net.conf, flat)
        for k, v in net.params.items():
            for pk, pv in v.items():
                np.testing.assert_allclose(np.asarray(params[k][pk]),
                                           np.asarray(pv), atol=1e-6,
                                           err_msg=f"{k}/{pk}")


class TestZooPretrainedFixture:
    def test_lenet_fixture_restore(self):
        """VERDICT r1 item: zoo init_pretrained restores from a locally
        generated fixture zip (stands in for ZooModel.java:52-81 downloads)."""
        from deeplearning4j_tpu.zoo import LeNet
        model = LeNet(num_classes=10)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "lenet.zip")
            spec = model.save_pretrained_fixture(path, flavor="mnist")
            assert "sha256" in spec
            net = model.init_pretrained("mnist")
            x = RNG.standard_normal((2, 1, 28, 28)).astype(np.float32)
            out = np.asarray(net.output(x))
            assert out.shape == (2, 10)
            np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-4)

    def test_checksum_validation(self):
        from deeplearning4j_tpu.zoo import LeNet
        model = LeNet(num_classes=10)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "lenet.zip")
            model.save_pretrained_fixture(path, flavor="mnist")
            model.pretrained["mnist"]["sha256"] = "0" * 64
            with pytest.raises(IOError):
                model.init_pretrained("mnist")


class TestRBM:
    def test_shapes_and_forward(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).list()
                .layer(RBM(n_out=6))
                .layer(OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        p = net.params["0"]
        assert p["W"].shape == (4, 6)
        assert p["b"].shape == (6,)
        assert p["vb"].shape == (4,)
        out = np.asarray(net.output(RNG.random((3, 4)).astype(np.float32)))
        assert out.shape == (3, 2)

    def test_cd_gradient_check(self):
        """CD gradient check: jax.grad of the free-energy-difference loss
        (with the Gibbs chain under stop_gradient) must equal finite
        differences of mean(F(p, v0) - F(p, vk)) with vk held FIXED — the
        stop_gradient is precisely what makes the chain a constant w.r.t.
        the perturbed parameters (the reference's CD update semantics,
        RBM.java:68 computeGradientAndScore)."""
        import jax
        import jax.numpy as jnp

        layer = RBM(n_in=4, n_out=3, k=2)
        key = jax.random.PRNGKey(0)
        params, _ = layer.init(key, InputType.feed_forward(4))
        params = {k: jnp.asarray(np.asarray(v), jnp.float64)
                  for k, v in params.items()}
        x = jnp.asarray(RNG.random((5, 4)), jnp.float64)

        grads = jax.grad(
            lambda p: layer.pretrain_loss(p, x, None, sample=False))(params)
        # freeze the chain at the evaluation point
        vk = layer.contrastive_divergence(params, x, None, sample=False)

        def frozen_loss(p):
            return float(jnp.mean(layer.free_energy(p, x) -
                                  layer.free_energy(p, vk)))

        eps = 1e-6
        for name in ("W", "b", "vb"):
            flat = np.asarray(params[name], np.float64).ravel()
            g_num = np.zeros_like(flat)
            for i in range(flat.size):
                plus = flat.copy(); plus[i] += eps
                minus = flat.copy(); minus[i] -= eps
                p_p = dict(params); p_p[name] = jnp.asarray(
                    plus.reshape(params[name].shape))
                p_m = dict(params); p_m[name] = jnp.asarray(
                    minus.reshape(params[name].shape))
                g_num[i] = (frozen_loss(p_p) - frozen_loss(p_m)) / (2 * eps)
            g_ana = np.asarray(grads[name], np.float64).ravel()
            np.testing.assert_allclose(g_ana, g_num, atol=1e-5, rtol=1e-4,
                                       err_msg=name)

    def test_pretrain_reduces_reconstruction_error(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.updater import Sgd

        # two binary prototype patterns + noise
        protos = np.array([[1, 1, 0, 0, 1, 0], [0, 0, 1, 1, 0, 1]], np.float32)
        idx = RNG.integers(0, 2, 128)
        x = protos[idx]
        flips = RNG.random(x.shape) < 0.05
        x = np.abs(x - flips.astype(np.float32))

        conf = (NeuralNetConfiguration.Builder()
                .seed(9).updater(Sgd(0.5)).list()
                .layer(RBM(n_out=4, k=1))
                .layer(OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        net = MultiLayerNetwork(conf).init()
        layer = net.layers[0]

        def recon_err(params):
            h = layer.prop_up(params, jnp.asarray(x))
            v = layer.prop_down(params, h)
            return float(np.mean((np.asarray(v) - x) ** 2))

        before = recon_err(net.params["0"])
        net.pretrain(DataSet(x, np.zeros((x.shape[0], 2), np.float32)),
                     epochs=12)
        after = recon_err(net.params["0"])
        assert after < before * 0.8, (before, after)

    def test_rbm_flat_vector_roundtrip(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).list()
                .layer(RBM(n_out=5))
                .layer(OutputLayer(n_out=2, loss="mse",
                                   activation="identity"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        import jax.numpy as jnp
        net.params["0"]["vb"] = jnp.asarray(RNG.standard_normal(4), jnp.float32)
        flat = d4.params_to_flat(net.conf, net.params, net.state)
        # PretrainParamInitializer layout: W, b, vb
        assert flat.size == 4 * 5 + 5 + 4 + 5 * 2 + 2
        params, _ = d4.params_from_flat(net.conf, flat)
        np.testing.assert_allclose(np.asarray(params["0"]["vb"]),
                                   np.asarray(net.params["0"]["vb"]),
                                   atol=1e-6)


class TestUpdaterState:
    """updaterState.bin both directions (ref: ModelSerializer.java:107-119
    write / :137-214 restore; view layout BaseMultiLayerUpdater.java:72-121,
    per-block state tensors applied at UpdaterBlock.java:104-142)."""

    def _net(self, updater, seed=9):
        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
        conf = (NeuralNetConfiguration.Builder()
                .seed(seed).updater(updater).weight_init("xavier").list()
                .layer(DenseLayer(n_out=6, activation="tanh"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(5))
                .build())
        return MultiLayerNetwork(conf).init()

    def _data(self):
        rng = np.random.default_rng(17)
        x = rng.standard_normal((16, 5)).astype(np.float32)
        y = np.zeros((16, 3), np.float32)
        y[np.arange(16), rng.integers(0, 3, 16)] = 1.0
        return x, y

    @pytest.mark.parametrize("make_updater", [
        lambda U: U.Adam(0.01), lambda U: U.Nesterovs(0.05, momentum=0.9),
        lambda U: U.RmsProp(0.01), lambda U: U.AdaGrad(0.05),
        lambda U: U.AdaDelta(), lambda U: U.Nadam(0.01),
        lambda U: U.AdaMax(0.01),
    ], ids=["adam", "nesterovs", "rmsprop", "adagrad", "adadelta", "nadam",
            "adamax"])
    def test_save_restore_training_continuation(self, make_updater):
        """Mid-training checkpoint resume must CONTINUE the optimizer, not
        restart it: save after 4 steps, restore, train 3 more — params match
        an uninterrupted run step for step (would fail with zeroed
        moments for every stateful updater here)."""
        from deeplearning4j_tpu.nn import updater as U
        x, y = self._data()
        net = self._net(make_updater(U))
        net.fit(x, y, epochs=4, batch_size=16)

        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "mid.zip")
            d4.save_dl4j_format(net, path)
            resumed = d4.restore_multi_layer_network(path)

        assert resumed.iteration_count == net.iteration_count
        st = resumed.updater_state
        # momentum buffers demonstrably non-zero after restore
        first_key = next(k for k in st if k != "t")
        mags = [float(np.abs(np.asarray(a)).max())
                for lp in st[first_key].values() for a in lp.values()]
        assert max(mags) > 0.0

        net.fit(x, y, epochs=3, batch_size=16)
        resumed.fit(x, y, epochs=3, batch_size=16)
        for k in net.params:
            for pk in net.params[k]:
                np.testing.assert_allclose(
                    np.asarray(resumed.params[k][pk]),
                    np.asarray(net.params[k][pk]), rtol=1e-4, atol=1e-6,
                    err_msg=f"{k}/{pk} ({type(make_updater(U)).__name__})")

    def test_block_layout_bn_breaks_blocks(self):
        """Hand-built flat view: BN's global mean/var use a NoOp updater
        (BatchNormalization.java:144-151) so they hold NO state and split
        the view into two blocks, each [m | v] over its params in view
        order (dense W,b,gamma,beta | output W,b)."""
        from deeplearning4j_tpu.nn import updater as U
        net = self._net(U.Adam(0.01))
        conf = net.conf
        # sizes: dense W 5*6, b 6; bn gamma 6, beta 6; out W 6*3, b 3
        b1 = 30 + 6 + 6 + 6   # block 1 params (48)
        b2 = 18 + 3           # block 2 params (21)
        flat = np.arange(2 * (b1 + b2), dtype=np.float64)
        st = d4.updater_state_from_flat(conf, flat, net.params,
                                        iteration_count=7)
        assert int(st["t"]) == 7
        # block 1: m = flat[0:48], v = flat[48:96]; W 'f'-order reshape
        np.testing.assert_allclose(
            np.asarray(st["m"]["0"]["W"]),
            flat[0:30].reshape((5, 6), order="F"))
        np.testing.assert_allclose(np.asarray(st["m"]["0"]["b"]),
                                   flat[30:36])
        np.testing.assert_allclose(np.asarray(st["m"]["1"]["gamma"]),
                                   flat[36:42])
        np.testing.assert_allclose(np.asarray(st["m"]["1"]["beta"]),
                                   flat[42:48])
        np.testing.assert_allclose(
            np.asarray(st["v"]["0"]["W"]),
            flat[48:78].reshape((5, 6), order="F"))
        # block 2 starts AFTER all of block 1's m and v
        np.testing.assert_allclose(
            np.asarray(st["m"]["2"]["W"]),
            flat[96:114].reshape((6, 3), order="F"))
        np.testing.assert_allclose(np.asarray(st["v"]["2"]["b"]),
                                   flat[135:138])
        # inverse: encode reproduces the wire layout bit for bit
        back = d4.updater_state_to_flat(conf, st)
        np.testing.assert_allclose(back, flat)

    def test_nesterov_hand_computed_step(self):
        """Imported momentum must drive the next step: one Nesterov update
        from an imported v equals the hand formula (v' = mu*v - lr*g;
        step = lr*g - mu*v' subtracted from params — ND4J NesterovsUpdater
        semantics)."""
        from deeplearning4j_tpu.nn import updater as U
        import jax.numpy as jnp
        upd = U.Nesterovs(0.1, momentum=0.9)
        params = {"0": {"W": jnp.asarray(np.ones((2, 2)), jnp.float32)}}
        v0 = np.full((2, 2), 0.5, np.float32)
        grads = {"0": {"W": jnp.asarray(np.full((2, 2), 0.2), jnp.float32)}}
        steps, new_state = upd.update(grads, {"v": {"0": {"W": jnp.asarray(v0)}}},
                                      params)
        v1 = 0.9 * v0 - 0.1 * 0.2
        np.testing.assert_allclose(np.asarray(new_state["v"]["0"]["W"]), v1,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(steps["0"]["W"]),
                                   0.1 * 0.2 - 0.9 * v1, rtol=1e-6)

    def test_lstm_state_gets_gate_permutation(self):
        """LSTM updater state must ride the same IFOG->IFCO column
        permutation as the weights (the state is per-parameter-element)."""
        from deeplearning4j_tpu.nn import updater as U
        conf = (NeuralNetConfiguration.Builder()
                .seed(2).updater(U.Nesterovs(0.1)).list()
                .layer(LSTM(n_out=3))
                .layer(RnnOutputLayer(n_out=2, loss="mse",
                                      activation="identity"))
                .set_input_type(InputType.recurrent(4, 5))
                .build())
        net = MultiLayerNetwork(conf).init()
        import jax.numpy as jnp
        # distinctive per-column state for the input-to-gate matrix
        st = {"v": {k: {pk: jnp.asarray(
            np.arange(np.prod(pv.shape), dtype=np.float32).reshape(pv.shape))
            for pk, pv in lp.items()} for k, lp in net.params.items()}}
        flat = d4.updater_state_to_flat(conf, st)
        back = d4.updater_state_from_flat(conf, flat, net.params)
        for k, lp in st["v"].items():
            for pk, pv in lp.items():
                np.testing.assert_allclose(np.asarray(back["v"][k][pk]),
                                           np.asarray(pv), atol=1e-6,
                                           err_msg=f"{k}/{pk}")

    def test_variable_layout_agrees_with_params_codec(self):
        """Drift guard for the three hand-maintained copies of the flat
        view layout: perturb each variable ONE at a time through
        params_to_flat and assert the changed flat positions are exactly
        the [offset, offset+size) window _variable_layout declares for
        it (catches any reordering/size divergence even when the total
        length stays equal)."""
        from deeplearning4j_tpu.nn.conf.layers import (
            BatchNormalization, GravesBidirectionalLSTM)
        import jax.numpy as jnp
        conv_conf = (NeuralNetConfiguration.Builder()
                     .seed(3).list()
                     .layer(ConvolutionLayer(n_out=3, kernel=[2, 2]))
                     .layer(BatchNormalization())
                     .layer(DenseLayer(n_out=4, activation="tanh"))
                     .layer(OutputLayer(n_out=2, loss="mse",
                                        activation="identity"))
                     .set_input_type(InputType.convolutional(2, 5, 5))
                     .build())
        rnn_conf = (NeuralNetConfiguration.Builder()
                    .seed(3).list()
                    .layer(GravesLSTM(n_out=3))
                    .layer(GravesBidirectionalLSTM(n_out=2))
                    .layer(RnnOutputLayer(n_out=2, loss="mse",
                                          activation="identity"))
                    .set_input_type(InputType.recurrent(4, 6))
                    .build())
        for conf in (conv_conf, rnn_conf):
            net = MultiLayerNetwork(conf).init()
            base = d4.params_to_flat(conf, net.params, net.state)
            layout = {(k, v): (off, size)
                      for (k, v, off, size, _) in d4._variable_layout(conf)}
            for lk, lp in net.params.items():
                for pk, pv in lp.items():
                    bumped = {k: dict(v) for k, v in net.params.items()}
                    bumped[lk][pk] = jnp.asarray(pv) + 1.0
                    flat2 = d4.params_to_flat(conf, bumped, net.state)
                    changed = np.nonzero(flat2 != base)[0]
                    # peepholes are stored as extra RW columns (one view
                    # variable in DL4J), so P* shares RW*'s window
                    win = {"P": "RW", "PF": "RWF", "PB": "RWB"}.get(pk, pk)
                    off, size = layout[(lk, win)]
                    assert changed.size == np.asarray(pv).size, (lk, pk)
                    assert changed.min() >= off and \
                        changed.max() < off + size, \
                        (lk, pk, off, size, changed.min(), changed.max())
                    if win == pk and pk not in ("RW", "RWF", "RWB"):
                        # plain variables must span their window exactly
                        assert changed.min() == off and \
                            changed.max() == off + size - 1, (lk, pk)


class TestComputationGraphImport:
    """DL4J ComputationGraph zip import/export (ref:
    ModelSerializer.restoreComputationGraph :137-214;
    ComputationGraphConfiguration JSON structure :62-85 — 'vertices' map,
    'vertexInputs', networkInputs/Outputs; flat params in topological
    order, ComputationGraph.java:418-479)."""

    def _residual_graph(self):
        """conv trunk with BN + elementwise residual + dense head —
        exercises LayerVertex, ElementWiseVertex, MergeVertex ordering."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).updater(__import__(
                    "deeplearning4j_tpu.nn.updater",
                    fromlist=["Adam"]).Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.convolutional(6, 6, 4))
                .add_layer("c1", ConvolutionLayer(n_out=4, kernel=[1, 1],
                                                  activation="identity"),
                           "in")
                .add_layer("bn", BatchNormalization(), "c1")
                .add_vertex("res",
                            __import__(
                                "deeplearning4j_tpu.nn.conf.graph_conf",
                                fromlist=["ElementWiseVertex"]
                            ).ElementWiseVertex(op="add"),
                            "c1", "bn")
                .add_layer("d1", DenseLayer(n_out=5, activation="tanh"),
                           "res",
                           preprocessor=__import__(
                               "deeplearning4j_tpu.nn.conf.preprocessors",
                               fromlist=["CnnToFeedForwardPreProcessor"]
                           ).CnnToFeedForwardPreProcessor(6, 6, 4))
                .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                              activation="softmax"), "d1")
                .set_outputs("out")
                .build())
        return ComputationGraph(conf).init()

    def test_cg_zip_roundtrip_outputs_match(self):
        net = self._residual_graph()
        x = RNG.standard_normal((3, 4, 6, 6)).astype(np.float32)
        want = np.asarray(net.output(x))
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "cg.zip")
            d4.save_dl4j_format(net, p)
            net2 = d4.restore_model(p)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        assert isinstance(net2, ComputationGraph)
        got = np.asarray(net2.output(x))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_cg_training_continuation_with_updater_state(self):
        """Mid-training CG checkpoint resumes the optimizer exactly."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net = self._residual_graph()
        rng = np.random.default_rng(5)
        x = rng.standard_normal((8, 4, 6, 6)).astype(np.float32)
        y = np.zeros((8, 3), np.float32)
        y[np.arange(8), rng.integers(0, 3, 8)] = 1.0
        for _ in range(4):
            net.fit(DataSet(x, y))
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "cg-mid.zip")
            d4.save_dl4j_format(net, p)
            resumed = d4.restore_model(p)
        assert resumed.iteration_count == net.iteration_count
        mags = [float(np.abs(np.asarray(a)).max())
                for lp in resumed.updater_state["m"].values()
                for a in lp.values()]
        assert max(mags) > 0.0
        for _ in range(3):
            net.fit(DataSet(x, y))
            resumed.fit(DataSet(x, y))
        for k in net.params:
            for pk in net.params[k]:
                np.testing.assert_allclose(
                    np.asarray(resumed.params[k][pk]),
                    np.asarray(net.params[k][pk]), rtol=1e-4, atol=1e-6,
                    err_msg=f"{k}/{pk}")

    def test_hand_written_dl4j_cg_json(self):
        """A DL4J-shaped CG JSON (LayerVertex/layerConf nesting, vertex
        wrapper objects, string fields per @JsonProperty names) imports
        into a working graph."""
        cfg = {
            "vertices": {
                "L0": {"LayerVertex": {"layerConf": {"layer": {
                    "dense": {"layerName": "L0", "nin": 5, "nout": 4,
                              "activationFn": {"TanH": {}},
                              "iUpdater": {"Nesterovs": {
                                  "learningRate": 0.05,
                                  "momentum": 0.9}}}}}}},
                "scaled": {"ScaleVertex": {"scaleFactor": 2.0}},
                "L1": {"LayerVertex": {"layerConf": {"layer": {
                    "output": {"layerName": "L1", "nin": 4, "nout": 2,
                               "activationFn": {"Softmax": {}},
                               "lossFn": {"LossMCXENT": {}}}}}}},
            },
            "vertexInputs": {"L0": ["in"], "scaled": ["L0"],
                             "L1": ["scaled"]},
            "networkInputs": ["in"],
            "networkOutputs": ["L1"],
        }
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = d4.computation_graph_configuration_from_dl4j(
            json.dumps(cfg),
            input_types={"in": InputType.feed_forward(5)})
        from deeplearning4j_tpu.nn.updater import Nesterovs
        assert isinstance(conf.updater, Nesterovs)
        net = ComputationGraph(conf).init()
        x = RNG.standard_normal((2, 5)).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 2)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)
        # scale vertex really doubles: compare against manual math
        w, b = (np.asarray(net.params["L0"][k]) for k in ("W", "b"))
        h = 2.0 * np.tanh(x @ w + b)
        w2, b2 = (np.asarray(net.params["L1"][k]) for k in ("W", "b"))
        logits = h @ w2 + b2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True),
                                   atol=1e-5)

    def test_lstm_seq_graph_roundtrip(self):
        """Recurrent graph with LastTimeStep vertex round-trips (gate
        permutation + vertex serde together)."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.graph_conf import LastTimeStepVertex
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).graph_builder()
                .add_inputs("seq")
                .set_input_types(InputType.recurrent(3, 7))
                .add_layer("lstm", GravesLSTM(n_out=4), "seq")
                .add_vertex("last", LastTimeStepVertex(mask_input="seq"),
                            "lstm")
                .add_layer("out", OutputLayer(n_out=2, loss="mse",
                                              activation="identity"),
                           "last")
                .set_outputs("out")
                .build())
        net = ComputationGraph(conf).init()
        import jax.numpy as jnp
        net.params["lstm"]["P"] = jnp.asarray(
            RNG.standard_normal((3, 4)), jnp.float32)
        x = RNG.standard_normal((2, 3, 7)).astype(np.float32)
        want = np.asarray(net.output(x))
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "cg-lstm.zip")
            d4.save_dl4j_format(net, p)
            net2 = d4.restore_model(p)
        np.testing.assert_allclose(np.asarray(net2.output(x)), want,
                                   atol=1e-5)

    def test_missing_input_types_clear_error(self):
        cfg = {"vertices": {}, "vertexInputs": {}, "networkInputs": ["in"],
               "networkOutputs": []}
        with pytest.raises(ValueError, match="input types"):
            d4.computation_graph_configuration_from_dl4j(json.dumps(cfg))

    def test_preprocessor_behind_layer_vertex_roundtrip(self):
        """Params must size on the POST-preprocessor type: BN behind a
        CnnToFeedForward preprocessor has flat-size features, not
        channels (the codec and _variable_layout share the items walk)."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            CnnToFeedForwardPreProcessor)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.updater import Adam
        from deeplearning4j_tpu.datasets.dataset import DataSet
        conf = (NeuralNetConfiguration.Builder()
                .seed(11).updater(Adam(0.01)).graph_builder()
                .add_inputs("img")
                .set_input_types(InputType.convolutional(4, 4, 2))
                .add_layer("bn", BatchNormalization(), "img",
                           preprocessor=CnnToFeedForwardPreProcessor(
                               4, 4, 2))
                .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                              activation="softmax"), "bn")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        assert np.asarray(net.params["bn"]["gamma"]).shape == (32,)
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 2, 4, 4)).astype(np.float32)
        y = np.zeros((4, 3), np.float32)
        y[np.arange(4), rng.integers(0, 3, 4)] = 1.0
        net.fit(DataSet(x, y))
        want = np.asarray(net.output(x))
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "cg-pre.zip")
            d4.save_dl4j_format(net, p)
            net2 = d4.restore_model(p)
        assert net2.conf.seed == 11  # seed round-trips for the RNG stream
        np.testing.assert_allclose(np.asarray(net2.output(x)), want,
                                   atol=1e-5)
        # updater state restored at the 32-feature sizing too
        np.testing.assert_allclose(
            np.asarray(net2.updater_state["m"]["bn"]["gamma"]),
            np.asarray(net.updater_state["m"]["bn"]["gamma"]), atol=1e-6)
