"""DataSet iterators.

TPU-native equivalent of the reference's iterator zoo:
- AsyncDataSetIterator (deeplearning4j-nn/.../datasets/iterator/
  AsyncDataSetIterator.java) — background prefetch so host ETL overlaps device
  compute; here a daemon thread + bounded queue (the device-affinity
  machinery of the ref's MagicQueue is unnecessary: JAX moves arrays at
  dispatch and overlaps H2D with compute).
- ExistingDataSetIterator, MultipleEpochsIterator, EarlyTerminationIterator,
  SamplingDataSetIterator, BenchmarkDataSetIterator
  (ref: datasets/iterator/*.java + impl/BenchmarkDataSetIterator.java).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator protocol matching the reference's DataSetIterator semantics
    (reset + iteration).

    Durable-cursor protocol (optional — resilience/durable.py): iterators
    that can resume a pass exactly implement

    - ``state() -> {"epoch": int, "pos": int}``: the consumer-visible
      position — pass index and batches already yielded this pass;
    - ``restore_state(state)``: the NEXT ``__iter__`` runs pass
      ``state["epoch"]`` (same shuffle order as an uninterrupted run)
      skipping the first ``state["pos"]`` batches.

    Checkpoint-based preemption recovery uses it to resume a fit killed
    mid-epoch bit-identical to a straight run; iterators without it fall
    back to approximate continuation (the interrupted epoch replays)."""

    def reset(self):
        pass

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError


def _as_arrays(x):
    """np.asarray, mapped over dicts (MultiDataSet-style named arrays)."""
    if x is None:
        return None
    if isinstance(x, dict):
        return {k: np.asarray(v) for k, v in x.items()}
    return np.asarray(x)


def _take(x, sel):
    if x is None:
        return None
    if isinstance(x, dict):
        return {k: v[sel] for k, v in x.items()}
    return x[sel]


def _num_examples(x):
    if isinstance(x, dict):
        return next(iter(x.values())).shape[0]
    return x.shape[0]


class ArrayDataSetIterator(DataSetIterator):
    """Batches over in-memory arrays. Features/labels may be dicts keyed by
    input/output name (ComputationGraph MultiDataSet equivalent)."""

    def __init__(self, features, labels=None, batch_size: int = 32,
                 features_mask=None, labels_mask=None, shuffle: bool = False,
                 seed: int = 0):
        self.features = _as_arrays(features)
        self.labels = _as_arrays(labels)
        self.features_mask = _as_arrays(features_mask)
        self.labels_mask = _as_arrays(labels_mask)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._pos = 0           # batches yielded in the current pass
        self._in_pass = False
        self._resume = None     # (epoch, pos) pending from restore_state

    def state(self):
        """Durable cursor (see DataSetIterator docstring): deterministic
        given (seed, epoch), so restoring it replays the exact remaining
        batches — shuffled passes included. A pending restore IS the
        cursor until the next pass consumes it."""
        if self._resume is not None:
            return {"epoch": self._resume[0], "pos": self._resume[1]}
        if self._in_pass:
            return {"epoch": self._epoch - 1, "pos": self._pos}
        return {"epoch": self._epoch, "pos": 0}

    def restore_state(self, state):
        self._resume = (int(state.get("epoch", 0)),
                        int(state.get("pos", 0)))

    def __iter__(self):
        if self._resume is not None:
            epoch, start = self._resume
            self._resume = None
        else:
            epoch, start = self._epoch, 0
        n = _num_examples(self.features)
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self._seed + epoch)
            rng.shuffle(idx)
        self._epoch = epoch + 1
        self._in_pass = True
        self._pos = start
        for bi, s in enumerate(range(0, n, self.batch_size)):
            if bi < start:
                continue
            sel = idx[s:s + self.batch_size]
            # pos advances BEFORE the yield: while the consumer holds
            # batch bi, the cursor already counts it as handed out — the
            # dispatch-boundary checkpoint has fully applied its update
            self._pos = bi + 1
            yield DataSet(
                _take(self.features, sel),
                _take(self.labels, sel),
                _take(self.features_mask, sel),
                _take(self.labels_mask, sel),
            )
        self._in_pass = False


class ExistingDataSetIterator(DataSetIterator):
    """Wraps a list/iterable of DataSets (ref: ExistingDataSetIterator.java)."""

    def __init__(self, datasets: Sequence[DataSet]):
        self.datasets = list(datasets)

    def __iter__(self):
        return iter(self.datasets)


class AsyncDataSetIterator(DataSetIterator):
    """Background-prefetch wrapper (ref: AsyncDataSetIterator.java, default
    queue depth 2 per device in the ref's fit loop :1161)."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, prefetch: int = 2):
        self.base = base
        self.prefetch = prefetch

    def reset(self):
        self.base.reset()

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        err: List[BaseException] = []
        stop = threading.Event()

        def worker():
            try:
                for ds in self.base:
                    # bounded put with a stop check so an abandoned consumer
                    # (e.g. early-termination break) can't pin the producer
                    # on a full queue forever
                    while not stop.is_set():
                        try:
                            q.put(ds, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surface worker errors to consumer
                err.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # generator closed (break/GC): release the producer thread
            stop.set()


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed-shape batches for benchmarking the training loop
    (ref: datasets/iterator/impl/BenchmarkDataSetIterator.java — yields
    the SAME pre-generated batch n times so the harness measures compute,
    not data generation)."""

    def __init__(self, features_shape, num_labels: int, total_batches: int,
                 seed: int = 42):
        import numpy as _np
        rng = _np.random.default_rng(seed)
        n = features_shape[0]
        x = rng.standard_normal(features_shape).astype(_np.float32)
        y = _np.zeros((n, num_labels), _np.float32)
        y[_np.arange(n), rng.integers(0, num_labels, n)] = 1.0
        self.batch = DataSet(x, y)
        self.total_batches = total_batches

    def __iter__(self):
        for _ in range(self.total_batches):
            yield self.batch


class MultipleEpochsIterator(DataSetIterator):
    """Repeat a base iterator N times (ref: MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base

    def __iter__(self):
        for _ in range(self.epochs):
            self.base.reset()
            yield from self.base


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches (ref: EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches

    def reset(self):
        self.base.reset()

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i >= self.max_batches:
                return
            yield ds


class SamplingDataSetIterator(DataSetIterator):
    """Sample batches with replacement from a full DataSet
    (ref: SamplingDataSetIterator.java)."""

    def __init__(self, full: DataSet, batch_size: int, total_batches: int, seed: int = 0):
        self.full = full
        self.batch_size = batch_size
        self.total_batches = total_batches
        self.seed = seed

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        n = self.full.num_examples()
        for _ in range(self.total_batches):
            sel = rng.integers(0, n, self.batch_size)
            yield DataSet(
                self.full.features[sel],
                None if self.full.labels is None else self.full.labels[sel],
            )


class JointParallelDataSetIterator(DataSetIterator):
    """Interleave several iterators (ref: datasets/iterator/parallel/
    JointParallelDataSetIterator.java — per-device feeds merged into one
    stream; inequality-terminating: stops at the shortest by default,
    continues through the longest with ``stop_on_first_exhausted=False``)."""

    def __init__(self, *iterators, stop_on_first_exhausted: bool = True):
        if not iterators:
            raise ValueError("need at least one iterator")
        self.iterators = list(iterators)
        self.stop_on_first_exhausted = stop_on_first_exhausted

    def reset(self):
        for it in self.iterators:
            it.reset()

    def __iter__(self):
        its = [iter(i) for i in self.iterators]
        alive = [True] * len(its)
        while any(alive):
            for k, it in enumerate(its):
                if not alive[k]:
                    continue
                try:
                    yield next(it)
                except StopIteration:
                    alive[k] = False
                    if self.stop_on_first_exhausted:
                        return


class FileSplitParallelDataSetIterator(DataSetIterator):
    """Batches from a directory of .npy/.npz shard files, decoded by a
    thread pool ahead of consumption (ref: datasets/iterator/parallel/
    FileSplitParallelDataSetIterator.java). Each .npz holds ``features``
    and optional ``labels``; a .npy holds features only."""

    def __init__(self, root_dir: str, pattern: str = "*.np[yz]",
                 batch_size: int = 32, num_threads: int = 2):
        import fnmatch
        self.paths = sorted(
            os.path.join(root_dir, f) for f in os.listdir(root_dir)
            if fnmatch.fnmatch(f, pattern))
        if not self.paths:
            raise FileNotFoundError(
                f"no files matching {pattern!r} under {root_dir!r}")
        self.batch_size = batch_size
        self.num_threads = max(1, num_threads)

    @staticmethod
    def _load(path):
        if path.endswith(".npz"):
            with np.load(path) as z:
                return z["features"], (z["labels"] if "labels" in z.files
                                       else None)
        return np.load(path), None

    def __iter__(self):
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        window = self.num_threads * 2  # bounded prefetch, not whole dataset
        with ThreadPoolExecutor(self.num_threads) as pool:
            pending = deque()
            paths = iter(self.paths)
            for p in paths:
                pending.append(pool.submit(self._load, p))
                if len(pending) >= window:
                    break
            while pending:
                feats, labels = pending.popleft().result()
                nxt = next(paths, None)
                if nxt is not None:
                    pending.append(pool.submit(self._load, nxt))
                n = feats.shape[0]
                for s in range(0, n, self.batch_size):
                    yield DataSet(
                        feats[s:s + self.batch_size],
                        None if labels is None
                        else labels[s:s + self.batch_size])
