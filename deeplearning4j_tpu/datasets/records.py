"""Record readers + record→DataSet adapters.

TPU-native equivalent of the DataVec layer the reference consumes plus its
adapters in deeplearning4j-core (SURVEY §2.4):
- RecordReader SPI (DataVec CSVRecordReader / CSVSequenceRecordReader /
  CollectionRecordReader) — here host-side readers, CSV decode through the
  native C++ runtime.
- datasets/datavec/RecordReaderDataSetIterator.java:441 (label column →
  one-hot or regression targets),
- SequenceRecordReaderDataSetIterator.java:467 (paired feature/label
  sequence readers, ALIGN_START/ALIGN_END padding + masks),
- RecordReaderMultiDataSetIterator.java:898 (named inputs/outputs built
  from column subsets).
"""

from __future__ import annotations

import itertools
import os
from enum import Enum
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet, one_hot
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.native import read_csv


# ---------------------------------------------------------------------------
# Record readers (DataVec-equivalent SPI)
# ---------------------------------------------------------------------------

class RecordReader:
    """One record per example: a 1-D float vector (ref: DataVec
    RecordReader)."""

    def records(self) -> Iterator[np.ndarray]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (ref: CollectionRecordReader)."""

    def __init__(self, rows: Sequence[Sequence[float]]):
        self._rows = [np.asarray(r, np.float32) for r in rows]

    def records(self):
        return iter(self._rows)


class CSVRecordReader(RecordReader):
    """CSV rows as records, parsed by the native runtime
    (ref: DataVec CSVRecordReader)."""

    def __init__(self, path: str, skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = path
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self._data: Optional[np.ndarray] = None

    def _load(self) -> np.ndarray:
        if self._data is None:
            self._data = read_csv(self.path, skip_header=self.skip_lines,
                                  delimiter=self.delimiter)
        return self._data

    def records(self):
        return iter(self._load())


class CSVSequenceRecordReader:
    """One CSV file per sequence: [T, C] arrays
    (ref: DataVec CSVSequenceRecordReader)."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self.paths = list(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def sequences(self) -> Iterator[np.ndarray]:
        for p in self.paths:
            yield read_csv(p, skip_header=self.skip_lines,
                           delimiter=self.delimiter)


class CollectionSequenceRecordReader:
    """In-memory sequences: list of [T, C] arrays."""

    def __init__(self, seqs: Sequence[np.ndarray]):
        self._seqs = [np.asarray(s, np.float32) for s in seqs]

    def sequences(self):
        return iter(self._seqs)


# ---------------------------------------------------------------------------
# Record → DataSet iterators
# ---------------------------------------------------------------------------

class RecordReaderDataSetIterator(DataSetIterator):
    """Minibatches from a RecordReader (ref:
    RecordReaderDataSetIterator.java:441).

    label_index: column holding the class index (one-hot encoded with
    num_classes) — or, with regression=True, label_index..label_index_to
    inclusive are continuous targets. label_index=None yields
    unlabeled features.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        if label_index is not None and not regression and num_classes is None:
            raise ValueError("classification needs num_classes")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to if label_index_to is not None \
            else label_index

    def __iter__(self):
        feats, labels = [], []
        for rec in self.reader.records():
            rec = np.asarray(rec, np.float32)
            if self.label_index is None:
                feats.append(rec)
            else:
                li, lj = self.label_index, self.label_index_to
                lab = rec[li:lj + 1]
                feat = np.concatenate([rec[:li], rec[lj + 1:]])
                feats.append(feat)
                if self.regression:
                    labels.append(lab)
                else:
                    labels.append(one_hot(lab[:1], self.num_classes)[0])
            if len(feats) == self.batch_size:
                yield self._emit(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._emit(feats, labels)
        self.reader.reset()

    @staticmethod
    def _emit(feats, labels):
        return DataSet(np.stack(feats),
                       np.stack(labels) if labels else None)


class AlignmentMode(Enum):
    """Sequence alignment for paired feature/label readers
    (ref: SequenceRecordReaderDataSetIterator.AlignmentMode)."""
    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence minibatches with masking (ref:
    SequenceRecordReaderDataSetIterator.java:467).

    Features come from ``reader``; labels either from the trailing
    column(s) of the same sequences (label_reader=None: last column is the
    class index) or from a separate label reader. Output layout is DL4J's
    [N, C, T] with [N, T] masks.
    """

    def __init__(self, reader, batch_size: int,
                 num_classes: Optional[int] = None,
                 label_reader=None, regression: bool = False,
                 alignment: AlignmentMode = AlignmentMode.ALIGN_START):
        self.reader = reader
        self.label_reader = label_reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.alignment = alignment

    def _pairs(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        if self.label_reader is None:
            for seq in self.reader.sequences():
                feat = seq[:, :-1]
                lab = seq[:, -1:]
                yield feat, self._encode(lab)
        else:
            sentinel = object()  # ref throws on count mismatch; stay lazy
            for feat, lab in itertools.zip_longest(
                    self.reader.sequences(), self.label_reader.sequences(),
                    fillvalue=sentinel):
                if feat is sentinel or lab is sentinel:
                    raise ValueError("feature and label readers yield "
                                     "different sequence counts")
                yield np.asarray(feat, np.float32), self._encode(lab)

    def _encode(self, lab: np.ndarray) -> np.ndarray:
        lab = np.asarray(lab, np.float32)
        if self.regression:
            return lab
        if self.num_classes is None:
            raise ValueError("classification needs num_classes")
        return one_hot(lab[:, 0], self.num_classes)

    def __iter__(self):
        batch: List[Tuple[np.ndarray, np.ndarray]] = []
        for pair in self._pairs():
            batch.append(pair)
            if len(batch) == self.batch_size:
                yield self._emit(batch)
                batch = []
        if batch:
            yield self._emit(batch)

    def _emit(self, batch) -> DataSet:
        n = len(batch)
        tf = max(f.shape[0] for f, _ in batch)
        tl = max(l.shape[0] for _, l in batch)
        if self.alignment is AlignmentMode.EQUAL_LENGTH:
            if any(f.shape[0] != l.shape[0] for f, l in batch):
                raise ValueError("EQUAL_LENGTH needs feature length == "
                                 "label length per sequence")
        t = max(tf, tl)
        cf = batch[0][0].shape[1]
        cl = batch[0][1].shape[1]
        x = np.zeros((n, cf, t), np.float32)
        y = np.zeros((n, cl, t), np.float32)
        xm = np.zeros((n, t), np.float32)
        ym = np.zeros((n, t), np.float32)
        for i, (f, l) in enumerate(batch):
            if self.alignment is AlignmentMode.ALIGN_END:
                fo, lo = t - f.shape[0], t - l.shape[0]
            else:
                fo, lo = 0, 0
            x[i, :, fo:fo + f.shape[0]] = f.T
            xm[i, fo:fo + f.shape[0]] = 1.0
            y[i, :, lo:lo + l.shape[0]] = l.T
            ym[i, lo:lo + l.shape[0]] = 1.0
        return DataSet(x, y, xm, ym)


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """Named multi-input/multi-output minibatches from column subsets
    (ref: RecordReaderMultiDataSetIterator.java:898 Builder —
    addReader/addInput/addOutput/addOutputOneHot).

    Usage::

        it = (RecordReaderMultiDataSetIterator.Builder(batch_size=32)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)            # columns 0..3
              .add_output_one_hot("csv", 4, 10)  # column 4 as 10 classes
              .build())
    """

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self.readers: Dict[str, RecordReader] = {}
            self.inputs: List[Tuple[str, Optional[int], Optional[int]]] = []
            self.outputs: List[Tuple[str, int, int, Optional[int]]] = []

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_input(self, name: str, col_from: Optional[int] = None,
                      col_to: Optional[int] = None):
            if col_from is not None and col_to is None:
                col_to = col_from  # single column
            self.inputs.append((name, col_from, col_to))
            return self

        def add_output(self, name: str, col_from: int, col_to: int):
            self.outputs.append((name, col_from, col_to, None))
            return self

        def add_output_one_hot(self, name: str, column: int,
                               num_classes: int):
            self.outputs.append((name, column, column, num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            return RecordReaderMultiDataSetIterator(self)

    def __init__(self, builder: "RecordReaderMultiDataSetIterator.Builder"):
        if not builder.readers:
            raise ValueError("no readers added")
        self._b = builder

    def __iter__(self):
        streams = {name: list(r.records())
                   for name, r in self._b.readers.items()}
        counts = {name: len(v) for name, v in streams.items()}
        if len(set(counts.values())) > 1:  # ref throws on count mismatch
            raise ValueError(f"readers disagree on record count: {counts}")
        n_total = next(iter(counts.values()))
        bs = self._b.batch_size
        for s in range(0, n_total, bs):
            ins, outs = [], []
            for name, cf, ct in self._b.inputs:
                rows = streams[name][s:s + bs]
                arr = np.stack([r if cf is None else r[cf:ct + 1]
                                for r in rows]).astype(np.float32)
                ins.append(arr)
            for name, cf, ct, ncls in self._b.outputs:
                rows = streams[name][s:s + bs]
                if ncls is None:
                    outs.append(np.stack([r[cf:ct + 1] for r in rows])
                                .astype(np.float32))
                else:
                    outs.append(one_hot(
                        np.array([r[cf] for r in rows]), ncls))
            yield MultiDataSet(ins, outs)
