"""Data pipeline: DataSet container, iterators, fetchers.

TPU-native equivalent of ND4J DataSet + deeplearning4j-core datasets/*
(RecordReaderDataSetIterator, MnistDataSetIterator, AsyncDataSetIterator...).
"""

from deeplearning4j_tpu.datasets.dataset import DataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (
    BenchmarkDataSetIterator,  # noqa: F401
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    ExistingDataSetIterator,
)
from deeplearning4j_tpu.datasets.normalizers import (  # noqa: F401
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    VGG16ImagePreProcessor,
    normalizer_from_dict,
)
from deeplearning4j_tpu.datasets.formatter import (  # noqa: F401
    LocalUnstructuredDataFormatter,
)
from deeplearning4j_tpu.datasets.fetchers import (  # noqa: F401
    CifarDataSetIterator,
    EmnistDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    MnistDataSetIterator,
    SvhnDataSetIterator,
)
