"""DataSet: one minibatch of (features, labels, masks).

TPU-native equivalent of ND4J org.nd4j.linalg.dataset.DataSet as consumed by
the reference's fit loops (MultiLayerNetwork.java:1204 hot loop). A plain
container of numpy/jax arrays; conversion to device arrays happens at the
jit boundary so host-side pipelines stay numpy-fast.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


def _sel(x, idx):
    """Index rows; dict-aware (ComputationGraph dict-keyed arrays)."""
    if x is None:
        return None
    if isinstance(x, dict):
        return {k: v[idx] for k, v in x.items()}
    return x[idx]


@dataclass
class DataSet:
    """features/labels are arrays, or dicts keyed by input/output name for
    ComputationGraph multi-input/-output batches."""

    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        f = self.features
        if isinstance(f, dict):
            f = next(iter(f.values()))
        return int(f.shape[0])

    def split_test_and_train(self, n_train: int):
        a = DataSet(_sel(self.features, slice(None, n_train)),
                    _sel(self.labels, slice(None, n_train)))
        b = DataSet(_sel(self.features, slice(n_train, None)),
                    _sel(self.labels, slice(n_train, None)))
        return a, b

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = _sel(self.features, idx)
        self.labels = _sel(self.labels, idx)
        self.features_mask = _sel(self.features_mask, idx)
        self.labels_mask = _sel(self.labels_mask, idx)

    def batch_by(self, batch_size: int):
        n = self.num_examples()
        for s in range(0, n, batch_size):
            sl = slice(s, s + batch_size)
            yield DataSet(_sel(self.features, sl), _sel(self.labels, sl),
                          _sel(self.features_mask, sl),
                          _sel(self.labels_mask, sl))


@dataclass
class MultiDataSet:
    """Multiple named-position inputs/outputs for ComputationGraph training
    (ref: org.nd4j.linalg.dataset.MultiDataSet as consumed by
    ComputationGraph.fit(MultiDataSetIterator))."""
    features: List[np.ndarray] = field(default_factory=list)
    labels: List[np.ndarray] = field(default_factory=list)
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0]) if self.features else 0


def one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    """One-hot encode integer class labels, validating the range: negative
    or >= num_classes labels raise instead of silently wrapping."""
    labels = np.asarray(labels).astype(np.int64).reshape(-1)
    if labels.size and (labels.min() < 0 or labels.max() >= num_classes):
        bad = labels[(labels < 0) | (labels >= num_classes)][0]
        raise ValueError(f"label {int(bad)} outside [0, {num_classes})")
    out = np.zeros((labels.shape[0], num_classes), np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out
