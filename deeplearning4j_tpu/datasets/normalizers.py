"""Data normalizers — fit statistics on a dataset, transform/revert
batches, and embed alongside checkpoints.

Equivalent of ND4J's DataNormalization family as DL4J uses it
(NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler;
ModelSerializer.addNormalizerToModel embeds the fitted normalizer in the
checkpoint zip so inference applies identical preprocessing —
util/ModelSerializer.java `addNormalizerToModel`/`restoreNormalizerFromFile`).

All three fit per-feature statistics over a DataSetIterator or arrays,
`transform` in place on DataSet objects or return-by-value on arrays, and
`revert_features`/`revert_labels` invert them. JSON serialization keeps
the checkpoint embed format human-readable.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

NORMALIZER_REGISTRY: Dict[str, type] = {}


def register_normalizer(cls):
    NORMALIZER_REGISTRY[cls.__name__] = cls
    return cls


def normalizer_from_dict(d: dict):
    d = dict(d)
    cls = NORMALIZER_REGISTRY[d.pop("@class")]
    return cls._from_dict(d)


def _feature_axes(x: np.ndarray):
    """Reduce over all axes except the feature axis: axis 1 for [N,F],
    [N,C,H,W] and [N,C,T] alike (DL4J stats are per-feature/channel)."""
    return tuple(i for i in range(x.ndim) if i != 1)


def _bshape(x: np.ndarray, v: np.ndarray):
    shape = [1] * x.ndim
    shape[1] = v.shape[0]
    return v.reshape(shape)



def _is_u8_nhwc(x: np.ndarray) -> bool:
    """Unambiguous uint8 NHWC decode-order batch? (channel-minor count in
    {1,3,4} while axis 1 is clearly spatial)."""
    return (x.dtype == np.uint8 and x.ndim == 4
            and x.shape[-1] in (1, 3, 4) and x.shape[1] not in (1, 3, 4))


class _BaseNormalizer:
    """fit / transform / revert protocol (ref: DataNormalization)."""

    fit_labels_flag = False

    def fit_label(self, enable: bool = True) -> None:
        """ref: DataNormalization.fitLabel — also normalize labels."""
        self.fit_labels_flag = enable

    # -- fitting -----------------------------------------------------------
    def fit(self, data) -> "_BaseNormalizer":
        """Accepts a DataSet, a DataSetIterator, or a features array."""
        if isinstance(data, DataSet):
            self._fit_arrays(np.asarray(data.features),
                             None if data.labels is None
                             else np.asarray(data.labels))
        elif hasattr(data, "__iter__") and not hasattr(data, "shape"):
            feats, labs = [], []
            for ds in data:
                feats.append(np.asarray(ds.features))
                if self.fit_labels_flag and ds.labels is not None:
                    labs.append(np.asarray(ds.labels))
            if hasattr(data, "reset"):
                data.reset()
            self._fit_arrays(np.concatenate(feats),
                             np.concatenate(labs) if labs else None)
        else:
            self._fit_arrays(np.asarray(data), None)
        return self

    def _fit_arrays(self, x, y):
        raise NotImplementedError

    # -- application -------------------------------------------------------
    def transform(self, data):
        """DataSet -> normalized in place (reference semantics);
        array -> normalized copy returned."""
        if isinstance(data, DataSet):
            data.features = self._tx(np.asarray(data.features),
                                     *self._feature_stats())
            if self.fit_labels_flag and data.labels is not None:
                data.labels = self._tx(np.asarray(data.labels),
                                       *self._label_stats_checked())
            return data
        return self._tx(np.asarray(data), *self._feature_stats())

    preprocess = transform  # DataNormalization.preProcess alias

    def revert_features(self, x) -> np.ndarray:
        return self._inv(np.asarray(x), *self._feature_stats())

    def revert_labels(self, y) -> np.ndarray:
        if not self.fit_labels_flag:
            return np.asarray(y)
        return self._inv(np.asarray(y), *self._label_stats_checked())

    def _label_stats_checked(self):
        stats = self._label_stats()
        if any(v is None for v in stats):
            raise RuntimeError(
                "fit_label(True) is set but label statistics were never "
                "fitted — fit() must see labeled DataSets")
        return stats

    # -- serde -------------------------------------------------------------
    def to_json(self) -> str:
        d = {"@class": type(self).__name__,
             "fitLabels": self.fit_labels_flag}
        d.update(self._stats_dict())
        return json.dumps(d)

    @classmethod
    def _from_dict(cls, d: dict):
        obj = cls._build(d)
        obj.fit_labels_flag = bool(d.get("fitLabels", False))
        return obj


@register_normalizer
class NormalizerStandardize(_BaseNormalizer):
    """Zero-mean unit-variance per feature (ref: NormalizerStandardize)."""

    def __init__(self):
        self.mean = self.std = None
        self.label_mean = self.label_std = None

    def _fit_arrays(self, x, y):
        self.mean = x.mean(axis=_feature_axes(x)).astype(np.float32)
        self.std = x.std(axis=_feature_axes(x)).astype(np.float32)
        self.std = np.where(self.std < 1e-8, 1.0, self.std)
        if self.fit_labels_flag and y is not None:
            self.label_mean = y.mean(axis=_feature_axes(y)).astype(np.float32)
            self.label_std = y.std(axis=_feature_axes(y)).astype(np.float32)
            self.label_std = np.where(self.label_std < 1e-8, 1.0,
                                      self.label_std)

    def _feature_stats(self):
        if self.mean is None:
            raise RuntimeError("normalizer not fitted")
        return self.mean, self.std

    def _label_stats(self):
        return self.label_mean, self.label_std

    @staticmethod
    def _tx(x, mean, std):
        return ((x - _bshape(x, mean)) / _bshape(x, std)).astype(np.float32)

    @staticmethod
    def _inv(x, mean, std):
        return (x * _bshape(x, std) + _bshape(x, mean)).astype(np.float32)

    def _stats_dict(self):
        d = {"mean": self.mean.tolist(), "std": self.std.tolist()}
        if self.label_mean is not None:
            d["labelMean"] = self.label_mean.tolist()
            d["labelStd"] = self.label_std.tolist()
        return d

    @classmethod
    def _build(cls, d):
        obj = cls()
        obj.mean = np.asarray(d["mean"], np.float32)
        obj.std = np.asarray(d["std"], np.float32)
        if "labelMean" in d:
            obj.label_mean = np.asarray(d["labelMean"], np.float32)
            obj.label_std = np.asarray(d["labelStd"], np.float32)
        return obj


@register_normalizer
class NormalizerMinMaxScaler(_BaseNormalizer):
    """Scale each feature into [lo, hi] (ref: NormalizerMinMaxScaler)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo = float(lo)
        self.hi = float(hi)
        self.fmin = self.fmax = None
        self.label_min = self.label_max = None

    def _fit_arrays(self, x, y):
        self.fmin = x.min(axis=_feature_axes(x)).astype(np.float32)
        self.fmax = x.max(axis=_feature_axes(x)).astype(np.float32)
        if self.fit_labels_flag and y is not None:
            self.label_min = y.min(axis=_feature_axes(y)).astype(np.float32)
            self.label_max = y.max(axis=_feature_axes(y)).astype(np.float32)

    def _feature_stats(self):
        if self.fmin is None:
            raise RuntimeError("normalizer not fitted")
        return self.fmin, self.fmax

    def _label_stats(self):
        return self.label_min, self.label_max

    def _tx(self, x, mn, mx):
        rng = np.where((mx - mn) < 1e-12, 1.0, mx - mn)
        unit = (x - _bshape(x, mn)) / _bshape(x, rng)
        return (unit * (self.hi - self.lo) + self.lo).astype(np.float32)

    def _inv(self, x, mn, mx):
        rng = np.where((mx - mn) < 1e-12, 1.0, mx - mn)
        unit = (x - self.lo) / (self.hi - self.lo or 1.0)
        return (unit * _bshape(x, rng) + _bshape(x, mn)).astype(np.float32)

    def _stats_dict(self):
        d = {"lo": self.lo, "hi": self.hi,
             "min": self.fmin.tolist(), "max": self.fmax.tolist()}
        if self.label_min is not None:
            d["labelMin"] = self.label_min.tolist()
            d["labelMax"] = self.label_max.tolist()
        return d

    @classmethod
    def _build(cls, d):
        obj = cls(d.get("lo", 0.0), d.get("hi", 1.0))
        obj.fmin = np.asarray(d["min"], np.float32)
        obj.fmax = np.asarray(d["max"], np.float32)
        if "labelMin" in d:
            obj.label_min = np.asarray(d["labelMin"], np.float32)
            obj.label_max = np.asarray(d["labelMax"], np.float32)
        return obj


@register_normalizer
class ImagePreProcessingScaler(_BaseNormalizer):
    """Pixel scaling u8 [0,255] -> [lo,hi], no fitting needed
    (ref: ImagePreProcessingScaler).

    Layout contract: 4-D image batches come OUT in NCHW (the framework's
    public layout). uint8 NHWC input (decode order) takes the fused
    native u8->f32 pack (native/src/image.cpp); uint8/float NCHW input is
    value-scaled in place. revert_features inverts the VALUE scaling only
    and keeps NCHW."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0,
                 max_pixel: float = 255.0):
        self.lo = float(lo)
        self.hi = float(hi)
        self.max_pixel = float(max_pixel)

    def _fit_arrays(self, x, y):  # stateless — ref: fit is a no-op
        pass

    def transform(self, data):
        if isinstance(data, DataSet):
            data.features = self.transform(np.asarray(data.features))
            return data
        x = np.asarray(data)
        scale = (self.hi - self.lo) / self.max_pixel
        if _is_u8_nhwc(x):
            # unambiguous NHWC decode order -> fused native pack to NCHW
            from deeplearning4j_tpu.native.image import u8hwc_to_f32chw
            out = u8hwc_to_f32chw(x, scale=scale)
            return out + self.lo if self.lo else out
        # NCHW (or non-image ranks): value scaling only, layout unchanged
        return (x.astype(np.float32) * scale + self.lo).astype(np.float32)

    preprocess = transform

    def revert_features(self, x) -> np.ndarray:
        scale = (self.hi - self.lo) / self.max_pixel
        return ((np.asarray(x) - self.lo) / scale).astype(np.float32)

    def _stats_dict(self):
        return {"lo": self.lo, "hi": self.hi, "maxPixel": self.max_pixel}

    @classmethod
    def _build(cls, d):
        return cls(d.get("lo", 0.0), d.get("hi", 1.0),
                   d.get("maxPixel", 255.0))


@register_normalizer
class VGG16ImagePreProcessor(_BaseNormalizer):
    """Mean-subtraction preprocessing for the ImageNet VGG nets
    (ref: org.nd4j.linalg.dataset.api.preprocessor.VGG16ImagePreProcessor,
    used by the zoo VGG16/VGG19): subtract the ImageNet per-channel RGB
    means — no scaling to [0,1]. Stateless (fit is a no-op). Accepts
    3-channel images only: float NCHW [N,3,H,W], a single [3,H,W] image,
    or uint8 NHWC decode order [N,H,W,3] (packed + subtracted in one
    fused native pass). Output is NCHW float32."""

    #: ImageNet training-set channel means, RGB order (the reference's
    #: VGG_MEAN_OFFSET values)
    RGB_MEANS = (123.68, 116.779, 103.939)

    def _fit_arrays(self, x, y):
        pass

    def fit_label(self, enabled: bool = True):
        if enabled:
            raise ValueError(
                "VGG16ImagePreProcessor transforms image FEATURES only "
                "(mean subtraction has no label analogue)")
        return self

    def _check_rgb(self, x: np.ndarray, axis: int) -> None:
        if x.shape[axis] != 3:
            raise ValueError(
                "VGG16ImagePreProcessor expects 3 RGB channels, got "
                f"shape {x.shape} (channel axis {axis})")

    def transform(self, data):
        if isinstance(data, DataSet):
            data.features = self.transform(np.asarray(data.features))
            return data
        x = np.asarray(data)
        means = np.asarray(self.RGB_MEANS, np.float32)
        if _is_u8_nhwc(x):
            self._check_rgb(x, 3)
            from deeplearning4j_tpu.native.image import u8hwc_to_f32chw
            # one fused pass: u8 NHWC -> f32 NCHW with the mean folded in
            return u8hwc_to_f32chw(x, scale=1.0, mean=means)
        x = x.astype(np.float32)
        if x.ndim == 4:                        # NCHW batch
            self._check_rgb(x, 1)
            return x - means[None, :, None, None]
        if x.ndim == 3:                        # single CHW image
            self._check_rgb(x, 0)
            return x - means[:, None, None]
        raise ValueError(
            f"VGG16ImagePreProcessor expects image input, got rank "
            f"{x.ndim} shape {x.shape}")

    preprocess = transform

    def revert_features(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        means = np.asarray(self.RGB_MEANS, np.float32)
        if x.ndim == 4:
            self._check_rgb(x, 1)
            return x + means[None, :, None, None]
        if x.ndim == 3:
            self._check_rgb(x, 0)
            return x + means[:, None, None]
        raise ValueError(
            f"VGG16ImagePreProcessor expects image input, got rank "
            f"{x.ndim} shape {x.shape}")

    def _stats_dict(self):
        return {}

    @classmethod
    def _build(cls, d):
        return cls()
