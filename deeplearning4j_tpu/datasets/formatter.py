"""Unstructured-directory → train/test split formatter.

Equivalent of the reference's LocalUnstructuredDataFormatter
(deeplearning4j-core/.../datasets/rearrange/LocalUnstructuredDataFormatter.java):
walk an unstructured data directory, derive each file's label either from
its parent DIRECTORY name or from the file NAME (the token between the
last '-' and the extension, e.g. ``img01-cat.jpg`` → ``cat``; the
reference's char-walk keeps the dot — dropped here), and copy everything
into ``<destination>/split/{train,test}/<label>/`` with a percent_train
split (test count = total - floor(total * percent_train), as the
reference computes it).
"""

from __future__ import annotations

import os
import random
import shutil
from typing import List, Optional


class LocalUnstructuredDataFormatter:
    """ref: LocalUnstructuredDataFormatter.java:29-187."""

    NAME = "name"
    DIRECTORY = "directory"

    def __init__(self, destination_root_dir: str, root_dir: str,
                 labeling_type: str = DIRECTORY,
                 percent_train: float = 0.8,
                 seed: Optional[int] = None):
        if labeling_type not in (self.NAME, self.DIRECTORY):
            raise ValueError(f"unknown labeling type {labeling_type!r}")
        self.root_dir = root_dir
        self.split_root = os.path.join(destination_root_dir, "split")
        if os.path.exists(self.split_root):
            # ref :60 "Train/test split already exists"
            raise RuntimeError("Train/test split already exists: "
                               + self.split_root)
        self.train_dir = os.path.join(self.split_root, "train")
        self.test_dir = os.path.join(self.split_root, "test")
        self.labeling_type = labeling_type
        self.percent_train = percent_train
        self.seed = seed
        self.num_examples_total = -1
        self.num_test_examples = -1
        self.num_examples_to_train_on = -1

    # -- labels ------------------------------------------------------------
    def get_path_label(self, path: str) -> str:
        """DIRECTORY labeling: parent directory name (ref getPathLabel)."""
        return os.path.basename(os.path.dirname(path))

    def get_name_label(self, path: str) -> str:
        """NAME labeling: token between the last '-' and the extension
        (ref getNameLabel; e.g. 'img01-cat.jpg' -> 'cat')."""
        base = os.path.basename(path)
        dot = base.rfind(".")
        if dot < 0:
            raise ValueError(f"no extension in {path!r}")
        dash = base.rfind("-", 0, dot)
        if dash < 0:
            raise ValueError(
                f"no '-' in {path!r}; a dash marks the label for NAME "
                "labeling")
        return base[dash + 1:dot]

    def _label(self, path: str) -> str:
        return (self.get_name_label(path) if self.labeling_type == self.NAME
                else self.get_path_label(path))

    # -- split -------------------------------------------------------------
    def _all_files(self) -> List[str]:
        out = []
        for d, _, names in os.walk(self.root_dir):
            out.extend(os.path.join(d, n) for n in names)
        return sorted(out)  # deterministic before the seeded shuffle

    def rearrange(self) -> None:
        """Copy every file under root_dir into
        split/{train,test}/<label>/ (ref rearrange :66-104)."""
        files = self._all_files()
        self.num_examples_total = len(files)
        self.num_examples_to_train_on = int(
            len(files) * self.percent_train)
        self.num_test_examples = len(files) - self.num_examples_to_train_on
        random.Random(self.seed).shuffle(files)
        for i, path in enumerate(files):
            train = i < self.num_examples_to_train_on
            dst_root = self.train_dir if train else self.test_dir
            dst_dir = os.path.join(dst_root, self._label(path))
            os.makedirs(dst_dir, exist_ok=True)
            dst = os.path.join(dst_dir, os.path.basename(path))
            # colliding basenames (same label from different subdirs) must
            # not silently overwrite — the split would shrink below the
            # reported counts
            n = 1
            while os.path.exists(dst):
                stem, ext = os.path.splitext(os.path.basename(path))
                dst = os.path.join(dst_dir, f"{stem}__{n}{ext}")
                n += 1
            shutil.copy2(path, dst)

    def get_new_destination(self, path: str, train: bool) -> str:
        """Destination path a file would be copied to (ref
        getNewDestination :110-146)."""
        root = self.train_dir if train else self.test_dir
        return os.path.join(root, self._label(path),
                            os.path.basename(path))

    # ref getter names
    def get_num_examples_total(self) -> int:
        return self.num_examples_total

    def get_num_examples_to_train_on(self) -> int:
        return self.num_examples_to_train_on

    def get_num_test_examples(self) -> int:
        return self.num_test_examples
