"""Dataset fetchers/iterators: MNIST, EMNIST, CIFAR-10, LFW, SVHN, Iris.

Equivalent of deeplearning4j-core base/MnistFetcher.java, EmnistFetcher.java,
datasets/fetchers/MnistDataFetcher.java, datasets/iterator/impl/
{Mnist,Emnist,Cifar,LFW,Iris}DataSetIterator, base/LFWDataFetcher.java and
the datasets/mnist/ IDX readers.

The reference downloads archives at construction time; this environment is
zero-egress, so fetchers read from a local data directory
(``data_dir`` arg or ``$DL4J_TPU_DATA_DIR``, default ``~/.dl4jtpu/data``).
Binary decode + normalization + batch assembly run through the native C++
IO runtime (deeplearning4j_tpu.native). ``synthetic=True`` generates a
deterministic stand-in dataset with the real shapes for pipeline testing
without the files.
"""

from __future__ import annotations

import gzip
import os
import shutil
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, one_hot as _one_hot
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.native import read_idx, u8_to_f32
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call

DEFAULT_DATA_DIR = os.environ.get(
    "DL4J_TPU_DATA_DIR", os.path.expanduser("~/.dl4jtpu/data"))

#: dataset acquisition IO (decompress/read off a possibly-remote mount)
#: retries transient OS errors with bounded backoff — the zero-egress
#: stand-in for the reference fetchers' download retry
_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.1, max_delay=1.0,
                        retry_on=(OSError,))

MNIST_FILES = {
    True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _decompress(gz: str, path: str) -> None:
    # decompress to a temp name then rename: an interrupted extraction
    # must not leave a truncated file at the final path
    tmp = path + f".tmp.{os.getpid()}"
    try:
        with gzip.open(gz, "rb") as fin, open(tmp, "wb") as fout:
            shutil.copyfileobj(fin, fout)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _resolve(data_dir: Optional[str], name: str) -> str:
    """Find ``name`` (or name.gz, decompressing next to it) under data_dir."""
    base = data_dir or DEFAULT_DATA_DIR
    path = os.path.join(base, name)
    if os.path.exists(path):
        return path
    gz = path + ".gz"
    if os.path.exists(gz):
        # transient IO (slow NFS mount, a concurrent extractor racing the
        # rename) retries with backoff; a genuinely bad archive still
        # raises after the bounded attempts
        retry_call(_decompress, gz, path, policy=_IO_RETRY,
                   op="dataset-decompress")
        return path
    raise FileNotFoundError(
        f"dataset file {name!r} not found under {base!r}. This build is "
        f"zero-egress: place the file there manually (or pass "
        f"synthetic=True for a deterministic stand-in).")


def _synthetic_images(n: int, shape: Tuple[int, ...], classes: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-dependent image-like data (NOT real data)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    imgs = rng.integers(0, 256, (n,) + shape, np.uint8)
    # plant a class-dependent mean shift so models can actually learn
    imgs = np.clip(imgs.astype(np.int32) +
                   (labels * (128 // classes))[:, None, None]
                   .reshape((n,) + (1,) * len(shape)), 0, 255)
    return imgs.astype(np.uint8), labels


class MnistDataSetIterator(ArrayDataSetIterator):
    """MNIST minibatches, features scaled to [0,1], labels one-hot
    (ref: MnistDataSetIterator + MnistDataFetcher semantics).

    Features are [N, 784] row vectors like the reference (use
    ``FeedForwardToCnnPreProcessor``/reshape for CNNs).
    """

    NUM_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, shuffle: Optional[bool] = None,
                 seed: int = 123, synthetic: bool = False,
                 num_examples: Optional[int] = None, flatten: bool = True,
                 _files: Optional[Tuple[str, str]] = None,
                 _label_offset: int = 0):
        if synthetic:
            imgs, labels = _synthetic_images(
                num_examples or (6000 if train else 1000), (28, 28),
                self.NUM_CLASSES, seed)
        else:
            img_f, lbl_f = _files or MNIST_FILES[train]
            imgs = read_idx(_resolve(data_dir, img_f))
            labels = read_idx(_resolve(data_dir, lbl_f)) - _label_offset
            if num_examples:
                imgs, labels = imgs[:num_examples], labels[:num_examples]
        x = u8_to_f32(imgs)  # native threaded [0,1] scaling
        x = x.reshape(x.shape[0], -1) if flatten \
            else x.reshape(x.shape[0], 1, *imgs.shape[1:])
        y = _one_hot(labels, self.NUM_CLASSES)
        super().__init__(x, y, batch_size=batch_size,
                         shuffle=(train if shuffle is None else shuffle),
                         seed=seed)


class EmnistDataSetIterator(MnistDataSetIterator):
    """EMNIST (ref: EmnistDataSetIterator.java). Same IDX format; split
    selects the file set and class count."""

    SPLITS = {"balanced": 47, "byclass": 62, "bymerge": 47, "digits": 10,
              "letters": 26, "mnist": 10}

    def __init__(self, batch_size: int, split: str = "balanced",
                 train: bool = True, data_dir: Optional[str] = None,
                 shuffle: Optional[bool] = None, seed: int = 123,
                 synthetic: bool = False,
                 num_examples: Optional[int] = None, flatten: bool = True):
        if split not in self.SPLITS:
            raise ValueError(f"unknown EMNIST split {split!r}; "
                             f"one of {sorted(self.SPLITS)}")
        self.NUM_CLASSES = self.SPLITS[split]
        part = "train" if train else "test"
        files = (f"emnist-{split}-{part}-images-idx3-ubyte",
                 f"emnist-{split}-{part}-labels-idx1-ubyte")
        super().__init__(
            batch_size, train=train, data_dir=data_dir, shuffle=shuffle,
            seed=seed, synthetic=synthetic, num_examples=num_examples,
            flatten=flatten, _files=files,
            _label_offset=1 if split == "letters" else 0)  # letters: 1-based


class CifarDataSetIterator(ArrayDataSetIterator):
    """CIFAR-10 from the python/bin binary batches
    (ref: CifarDataSetIterator.java). Features [N,3,32,32] in [0,1]."""

    NUM_CLASSES = 10
    TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
    TEST_FILES = ["test_batch.bin"]

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 123,
                 synthetic: bool = False,
                 num_examples: Optional[int] = None):
        if synthetic:
            imgs, labels = _synthetic_images(
                num_examples or 2000, (3, 32, 32), self.NUM_CLASSES, seed)
        else:
            parts = []
            for name in (self.TRAIN_FILES if train else self.TEST_FILES):
                raw = np.fromfile(_resolve(data_dir, name), np.uint8)
                parts.append(raw.reshape(-1, 3073))  # [label + 3072 pixels]
            recs = np.concatenate(parts)
            if num_examples:
                recs = recs[:num_examples]
            labels = recs[:, 0]
            imgs = recs[:, 1:].reshape(-1, 3, 32, 32)
        x = u8_to_f32(np.ascontiguousarray(imgs)).reshape(-1, 3, 32, 32)
        y = _one_hot(labels, self.NUM_CLASSES)
        super().__init__(x, y, batch_size=batch_size, shuffle=train,
                         seed=seed)


class LFWDataSetIterator(ArrayDataSetIterator):
    """Labeled Faces in the Wild (ref: datasets/iterator/impl/
    LFWDataSetIterator.java + fetchers/LFWDataFetcher.java).

    Reads the standard extracted layout ``<data_dir>/lfw/<person>/<img>``
    (one directory per identity, jpg/png inside), decodes + resizes on the
    host, labels = identity index sorted by name. ``num_labels`` keeps the
    N most-frequent identities like the reference's subset mode.
    Features [N, C, H, W] scaled to [0,1]. ``synthetic=True`` generates a
    deterministic stand-in with the real shapes (zero-egress testing).
    """

    def __init__(self, batch_size: int, image_shape: Tuple[int, int, int] = (250, 250, 3),
                 num_examples: Optional[int] = None,
                 num_labels: Optional[int] = None, train: bool = True,
                 split_train_test: float = 1.0,
                 data_dir: Optional[str] = None, seed: int = 123,
                 synthetic: bool = False):
        h, w, c = image_shape
        if synthetic:
            n = num_examples or 200
            classes = num_labels or 10
            imgs, labels = _synthetic_images(n, (c, h, w), classes, seed)
            x = u8_to_f32(np.ascontiguousarray(imgs)).reshape(-1, c, h, w)
            self.num_classes = classes
            self.label_names = [f"person_{i}" for i in range(classes)]
        else:
            from PIL import Image
            base = os.path.join(data_dir or DEFAULT_DATA_DIR, "lfw")
            if not os.path.isdir(base):
                raise FileNotFoundError(
                    f"LFW directory {base!r} not found. This build is "
                    "zero-egress: extract lfw.tgz there manually (or pass "
                    "synthetic=True).")
            exts = (".jpg", ".jpeg", ".png")
            people = sorted(d for d in os.listdir(base)
                            if os.path.isdir(os.path.join(base, d)))
            counts = {p: sum(1 for f in os.listdir(os.path.join(base, p))
                             if f.lower().endswith(exts))
                      for p in people}
            if num_labels:
                people = sorted(sorted(people, key=lambda p: -counts[p])
                                [:num_labels])
            self.label_names = people
            self.num_classes = len(people)
            xs, ys = [], []
            for li, person in enumerate(people):
                pdir = os.path.join(base, person)
                for fn in sorted(os.listdir(pdir)):
                    if not fn.lower().endswith(exts):
                        continue
                    img = Image.open(os.path.join(pdir, fn))
                    img = img.convert("RGB" if c == 3 else "L")
                    img = img.resize((w, h))
                    a = np.asarray(img, np.uint8)
                    if c == 1:
                        a = a[:, :, None]
                    xs.append(a.transpose(2, 0, 1))  # HWC -> CHW
                    ys.append(li)
                    if num_examples and len(xs) >= num_examples:
                        break
                if num_examples and len(xs) >= num_examples:
                    break
            imgs = np.stack(xs)
            labels = np.asarray(ys)
            x = u8_to_f32(np.ascontiguousarray(imgs)).reshape(imgs.shape)
        if split_train_test < 1.0:
            cut = int(len(x) * split_train_test)
            rng = np.random.default_rng(seed)
            order = rng.permutation(len(x))
            keep = order[:cut] if train else order[cut:]
            x, labels = x[keep], labels[keep]
        y = _one_hot(labels, self.num_classes)
        super().__init__(x, y, batch_size=batch_size, shuffle=train,
                         seed=seed)


class SvhnDataSetIterator(ArrayDataSetIterator):
    """Street View House Numbers, cropped-digits format (ref: the
    SVHN fetcher family in later DL4J; 0.9.x lists SVHN in its dataset
    roster). Reads the stanford ``train_32x32.mat``/``test_32x32.mat``
    (matlab v5 via scipy.io) from the data dir. Features [N,3,32,32] in
    [0,1]; label "10" (zero digit) remapped to class 0 like the usual
    SVHN convention."""

    NUM_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True,
                 data_dir: Optional[str] = None, seed: int = 123,
                 synthetic: bool = False,
                 num_examples: Optional[int] = None):
        if synthetic:
            imgs, labels = _synthetic_images(
                num_examples or 2000, (3, 32, 32), self.NUM_CLASSES, seed)
            x = u8_to_f32(np.ascontiguousarray(imgs)).reshape(-1, 3, 32, 32)
        else:
            from scipy.io import loadmat
            name = "train_32x32.mat" if train else "test_32x32.mat"
            mat = loadmat(_resolve(data_dir, name))
            imgs = mat["X"]            # [32, 32, 3, N]
            labels = mat["y"].ravel().astype(np.int64)
            labels[labels == 10] = 0   # '0' digit stored as 10
            imgs = np.ascontiguousarray(imgs.transpose(3, 2, 0, 1))  # NCHW
            if num_examples:
                imgs, labels = imgs[:num_examples], labels[:num_examples]
            x = u8_to_f32(imgs).reshape(imgs.shape)
        y = _one_hot(labels, self.NUM_CLASSES)
        super().__init__(x, y, batch_size=batch_size, shuffle=train,
                         seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Iris (ref: IrisDataSetIterator.java). Reads ``iris.csv``
    (4 features + integer label per row) from the data dir; without the
    file, generates a deterministic 3-class Gaussian stand-in with the
    iris shape (150x4) — synthetic, clearly not Fisher's measurements."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 data_dir: Optional[str] = None, seed: int = 6):
        try:
            from deeplearning4j_tpu.native import read_csv
            data = read_csv(_resolve(data_dir, "iris.csv"))
            x, labels = data[:, :4], data[:, 4].astype(np.int64)
        except FileNotFoundError:
            rng = np.random.default_rng(seed)
            centers = np.array([[5.0, 3.4, 1.5, 0.2],
                                [5.9, 2.8, 4.3, 1.3],
                                [6.6, 3.0, 5.6, 2.0]], np.float32)
            labels = np.repeat(np.arange(3), 50)
            x = (centers[labels] +
                 rng.normal(0, 0.3, (150, 4))).astype(np.float32)
        x, labels = x[:num_examples], labels[:num_examples]
        super().__init__(x.astype(np.float32), _one_hot(labels, 3),
                         batch_size=batch_size, shuffle=False, seed=seed)
