"""Fault flight recorder: a post-mortem artifact for terminal failures.

An escalated serving fault (``GenerationEngine._break``), a supervisor
budget exhaustion, a fleet with no replica left to place on
(``NoReplicaAvailable``), or a training divergence
(``DivergenceError``) currently leaves ONE trace of itself: the raised
exception. Every question a post-mortem actually asks — what was the
queue doing, which requests were in flight and where had they been,
what did the ops timeline look like in the minute before — dies with
the process. This module dumps that context to disk at the moment of
failure, the way an aircraft flight recorder preserves the approach,
not just the impact.

One artifact per dump, JSONL, written ATOMICALLY (tmp sibling +
``os.replace`` via ``resilience.durable`` — a crash mid-dump leaves no
torn artifact):

    line 1:  header {trigger, error, time, pid, health, queue, extra}
    lines:   one per ring-buffer event (the ops-timeline tail)
    lines:   one per request trace ({"trace": ...} payload form)

Budget-capped on every axis so a dump can never OOM or disk-fill its
way into being a second incident: the event tail, the trace count, and
the total serialized bytes are all bounded, and dumps themselves are
rate-limited per trigger with a process-wide cap (a crash-looping
engine writes a handful of artifacts, not thousands).

Trigger matrix (see ARCHITECTURE.md "Structured events & request
tracing"):

    ``engine_break``          GenerationEngine._break (terminal fail-all)
    ``supervisor_escalation`` EngineSupervisor budget exhausted / rebuild
                              failed (fires just before engine_break —
                              the per-trigger rate limit keeps both)
    ``no_replica``            FleetRouter.submit with every replica
                              refusing / nothing healthy left
    ``divergence``            DivergenceWatchdog raising DivergenceError

All dumps are best-effort: ``maybe_dump`` never raises into the failure
path that invoked it.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.monitoring.events import global_event_log
from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

log = logging.getLogger(__name__)

__all__ = ["flight_dir", "last_record_path", "maybe_dump",
           "read_record", "reset_for_tests", "set_flight_dir"]

FLIGHT_DUMPS = "dl4jtpu_flight_records_total"

#: budget caps — the artifact must stay a bundle, not a database
MAX_EVENTS = 500
MAX_TRACES = 16
MAX_BYTES = 2 * 1024 * 1024
#: rate limits — a crash loop writes a handful of artifacts, not 1000s
MIN_INTERVAL_S = 10.0
MAX_DUMPS_PER_PROCESS = 32

_mu = threading.Lock()
_dir: Optional[str] = None
_last_by_trigger: Dict[str, float] = {}
_dump_count = 0
_last_path: Optional[str] = None


def set_flight_dir(path: Optional[str]) -> None:
    """Where artifacts land (None restores the default:
    ``$DL4JTPU_FLIGHT_DIR`` or ``<tmpdir>/dl4jtpu_flight``)."""
    global _dir
    with _mu:
        _dir = path


def flight_dir() -> str:
    with _mu:
        if _dir is not None:
            return _dir
    return os.environ.get(
        "DL4JTPU_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "dl4jtpu_flight"))


def last_record_path() -> Optional[str]:
    """Path of the most recent dump this process wrote (tests /
    operator logs)."""
    with _mu:
        return _last_path


def reset_for_tests() -> None:
    """Drop the rate-limit state so a test can dump deterministically."""
    global _dump_count, _last_path
    with _mu:
        _last_by_trigger.clear()
        _dump_count = 0
        _last_path = None


def _jsonable(obj: Any) -> Any:
    """Lossy-but-total JSON coercion: a flight record must always
    serialize, whatever a health()/queue payload happens to carry."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        return repr(obj)


def maybe_dump(trigger: str, error: Optional[BaseException] = None,
               health: Optional[dict] = None,
               queue: Optional[Any] = None,
               traces: Optional[List[Any]] = None,
               extra: Optional[dict] = None,
               registry: Optional[MetricsRegistry] = None
               ) -> Optional[str]:
    """Write one flight-record artifact if the budget allows; returns
    its path, or None when rate-limited / disabled / failed. Never
    raises — this runs inside failure paths that must stay failure
    paths, not become new ones.

    `traces` accepts ``serving.request.RequestTrace`` objects (or any
    object with ``to_payload()``), newest-first preferred — only the
    first ``MAX_TRACES`` are kept."""
    global _dump_count, _last_path
    now = time.monotonic()
    with _mu:
        if _dump_count >= MAX_DUMPS_PER_PROCESS:
            return None
        last = _last_by_trigger.get(trigger)
        if last is not None and now - last < MIN_INTERVAL_S:
            return None
        _last_by_trigger[trigger] = now
        _dump_count += 1
    try:
        return _dump(trigger, error, health, queue, traces, extra,
                     registry)
    except Exception:  # noqa: BLE001 — a recorder must never re-fail
        log.exception("flight recorder: dump for trigger %r failed",
                      trigger)
        # refund the process-wide slot: N transient write failures
        # must not permanently kill the recorder (the per-trigger
        # rate-limit stamp stays — it bounds the retry rate instead)
        with _mu:
            _dump_count -= 1
        return None


def _dump(trigger, error, health, queue, traces, extra,
          registry) -> Optional[str]:
    global _last_path
    events = global_event_log().tail(MAX_EVENTS)
    qdict = None
    if queue is not None:
        qdict = (dict(depth=queue.depth,
                      per_priority={str(k): v for k, v
                                    in queue.per_priority.items()},
                      oldest_wait_s=queue.oldest_wait_s)
                 if hasattr(queue, "per_priority") else _jsonable(queue))
    header = {
        "record": "dl4jtpu_flight", "version": 1,
        "trigger": trigger,
        "error": repr(error) if error is not None else None,
        "time": time.time(), "pid": os.getpid(),
        "health": _jsonable(health),
        "queue": qdict,
        "extra": _jsonable(extra),
        "events": len(events),
        "events_dropped": global_event_log().dropped_total,
    }
    lines = [json.dumps(header, default=repr)]
    for ev in events:
        lines.append(json.dumps(ev.as_dict(), default=repr))
    n_traces = 0
    for tr in (traces or [])[:MAX_TRACES]:
        payload = tr.to_payload() if hasattr(tr, "to_payload") else tr
        lines.append(json.dumps({"trace": _jsonable(payload)},
                                default=repr))
        n_traces += 1
    # the byte budget trims the event tail first (oldest events are the
    # cheapest history to lose), never the header or the traces
    while len(lines) > 1 + n_traces \
            and sum(len(l) + 1 for l in lines) > MAX_BYTES:
        lines.pop(1)
    d = flight_dir()
    os.makedirs(d, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S")
    path = os.path.join(
        d, f"flight_{trigger}_{stamp}_{os.getpid()}_"
           f"{global_event_log().total_emitted}.jsonl")
    from deeplearning4j_tpu.resilience.durable import atomic_write_text
    atomic_write_text(path, "\n".join(lines) + "\n")
    with _mu:
        _last_path = path
    (registry or global_registry()).counter(
        FLIGHT_DUMPS, "Flight-record artifacts written, by trigger",
        ("trigger",)).inc(trigger=trigger)
    global_event_log().emit("flight", "dump", trigger=trigger, path=path)
    log.error("flight recorder: %s -> %s (%d events, %d traces)",
              trigger, path, len(lines) - 1 - n_traces, n_traces)
    return path


def read_record(path: str) -> dict:
    """Parse one artifact back into {header, events, traces} (tests,
    offline analysis)."""
    header, events, traces = None, [], []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            if header is None:
                header = obj
            elif "trace" in obj and "category" not in obj:
                traces.append(obj["trace"])
            else:
                events.append(obj)
    return {"header": header, "events": events, "traces": traces}
