"""Process-wide structured event log: the fleet's flight timeline.

The PR 1 metrics layer answers "how much / how fast" in aggregate; this
module answers "what happened, in what order". One process-wide,
thread-safe, sync-free ring buffer of typed :class:`Event` records that
every layer of the serving and resilience stack appends to — engine
rebuilds and brownout transitions, fleet migrations and scale events,
elastic re-meshes, checkpoint commits, divergence restarts — queryable
live (``tail()``, the UIServer ``/events`` endpoint, ``health()``
``last_events`` payloads) and dumped wholesale by the fault flight
recorder (``monitoring/flightrecorder.py``) when something terminal
fires.

Contract (the reason hot paths may call ``emit`` freely):

- **host-side only** — an event is a couple of dict inserts and two
  clock reads; no device syncs, no jax imports, no new jit inputs, so
  tracing stays ON by default with zero retraces (recompile-watcher
  pinned in tests/test_events.py);
- **bounded** — a fixed-capacity ring: when full, the OLDEST event is
  overwritten and ``dl4jtpu_events_dropped_total`` counts the loss (an
  event storm costs memory of the past, never memory of the process);
- **non-blocking export** — readers snapshot the ring under the lock
  and filter/serialize OUTSIDE it, so a slow scrape or a fat JSON dump
  never stalls an ``emit`` (and the depth gauge reads a plain int,
  lock-free, so the registry scrape can never deadlock against an
  emitter incrementing the dropped counter).

Per-REQUEST detail deliberately does NOT ride this log (one line per
token across a fleet would be pure ring churn): request lifecycle lives
in ``serving.request.RequestTrace``, attached to each stream handle and
carried across replicas by the request ledger. This log is the
OPS-level timeline those traces interleave with.

See ARCHITECTURE.md "Structured events & request tracing".
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

__all__ = ["Event", "EventLog", "declare_event_series", "emit",
           "events_enabled", "global_event_log", "set_events_enabled"]

EVENTS_DEPTH = "dl4jtpu_events_depth"
EVENTS_DROPPED = "dl4jtpu_events_dropped_total"

#: default ring capacity — a few minutes of fleet churn; the flight
#: recorder caps its own tail separately
DEFAULT_CAPACITY = 2048

#: event categories in use across the stack (open vocabulary — these
#: are the taxonomy ARCHITECTURE.md documents, not an enum gate):
#: ``serving`` (engine lifecycle: rebuild/escalate/break/drain/shed/
#: early_reject/brownout), ``fleet`` (router: replica_join/replica_dead/
#: migration/rebalance/scale_out/scale_in/autoscale/generation),
#: ``resilience`` (remesh/checkpoint_save/checkpoint_commit/rollback/
#: restart/preemption/divergence), ``flight`` (recorder dumps),
#: ``transport`` (cross-process fleet mailbox/journal: admit/revoke/
#: duplicate/quarantine/nack/replace — serving/fleet/transport.py).
KNOWN_CATEGORIES = ("serving", "fleet", "resilience", "flight",
                    "transport")


class Event:
    """One timeline entry: monotonic + wall timestamps, a category, a
    short name, and a flat attrs dict. Immutable by convention (the
    ring hands out references; mutating one would rewrite history)."""

    __slots__ = ("seq", "mono", "wall", "category", "name", "attrs")

    def __init__(self, seq: int, mono: float, wall: float,
                 category: str, name: str, attrs: Dict[str, Any]):
        self.seq = seq
        self.mono = mono
        self.wall = wall
        self.category = category
        self.name = name
        self.attrs = attrs

    def as_dict(self) -> Dict[str, Any]:
        return {"seq": self.seq, "mono": self.mono, "wall": self.wall,
                "category": self.category, "name": self.name,
                "attrs": dict(self.attrs)}

    def __repr__(self):
        return (f"Event({self.seq}, {self.category}.{self.name}, "
                f"{self.attrs})")


#: process-wide enable switch (tracing is ON by default; the bench A/B
#: flips it off to price the instrumentation). RequestTrace consults
#: the same flag, so one switch silences the whole event layer.
_enabled = True


def set_events_enabled(flag: bool) -> bool:
    """Flip structured-event tracing process-wide; returns the previous
    value (so benches can restore it). Disabled = ``emit`` and
    ``RequestTrace.record`` become no-ops; already-buffered events stay
    readable."""
    global _enabled
    prev = _enabled
    _enabled = bool(flag)
    return prev


def events_enabled() -> bool:
    return _enabled


class EventLog:
    """Thread-safe bounded ring of :class:`Event` records."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[MetricsRegistry] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: "deque[Event]" = deque(maxlen=self.capacity)
        self._seq = 0
        #: plain ints, read lock-free by the depth gauge and the
        #: dropped-counter reconciler — never take self._lock from a
        #: registry-scrape callback (the emit path increments the
        #: registry counter while NOT holding self._lock, for the same
        #: no-lock-order-cycle reason)
        self._dropped = 0
        self._registry = registry
        self._dropped_handle = None
        self._sink_lock = threading.Lock()
        self._sink_path: Optional[str] = None

    # -- write side ----------------------------------------------------
    def emit(self, category: str, name: str, **attrs) -> Optional[Event]:
        """Append one event (no-op returning None while tracing is
        disabled). ``attrs`` values should be small JSON-able scalars —
        the ring is memory, the JSONL sink is a file, and the flight
        recorder serializes tails wholesale."""
        if not _enabled:
            return None
        mono, wall = time.monotonic(), time.time()
        with self._lock:
            self._seq += 1
            ev = Event(self._seq, mono, wall, str(category), str(name),
                       attrs)
            dropped = len(self._buf) >= self.capacity
            self._buf.append(ev)
            if dropped:
                self._dropped += 1
        if dropped:
            h = self._dropped_handle
            if h is not None:
                h.inc()           # outside self._lock: no ABBA with scrape
        sink = self._sink_path
        if sink is not None:
            self._sink_write(ev)
        return ev

    # -- read side (snapshot under lock, work outside it) --------------
    def tail(self, n: Optional[int] = None, category: Optional[str] = None,
             match: Optional[Dict[str, Any]] = None) -> List[Event]:
        """The most recent `n` events (oldest first), optionally
        filtered by category and/or exact attr matches. Non-mutating;
        filtering and any serialization happen on a snapshot taken
        under the lock, never while holding it."""
        with self._lock:
            snap = list(self._buf)
        if category is not None:
            snap = [e for e in snap if e.category == category]
        if match:
            snap = [e for e in snap
                    if all(e.attrs.get(k) == v for k, v in match.items())]
        if n is not None and n >= 0:
            snap = snap[-n:] if n else []   # [-0:] is the WHOLE list
        return snap

    def depth(self) -> int:
        return len(self._buf)       # deque len: atomic, lock-free

    @property
    def dropped_total(self) -> int:
        return self._dropped

    @property
    def total_emitted(self) -> int:
        return self._seq

    def clear(self) -> None:
        """Drop everything (tests; the dropped/seq counters survive —
        they are process-lifetime accounting, not buffer state)."""
        with self._lock:
            self._buf.clear()

    # -- optional JSONL sink -------------------------------------------
    def attach_jsonl(self, path: Optional[str]) -> None:
        """Stream every future event as one JSON line appended to
        `path` (None detaches). Best-effort: a failing write disables
        the sink rather than breaking the emitter."""
        with self._sink_lock:
            self._sink_path = path

    def _sink_write(self, ev: Event) -> None:
        with self._sink_lock:
            path = self._sink_path
            if path is None:
                return
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(ev.as_dict(), default=repr) + "\n")
            except OSError:
                self._sink_path = None   # a dead sink must not wedge emit

    # -- telemetry -----------------------------------------------------
    def declare_series(self, registry: Optional[MetricsRegistry] = None
                       ) -> None:
        """Register the event-log depth gauge + dropped counter (called
        from ``monitoring.ensure_started`` for the global log). The
        depth gauge reads a lock-free len, so a registry scrape can
        never block on — or hold — the event-log lock."""
        r = registry or self._registry or global_registry()
        r.gauge(EVENTS_DEPTH, "Structured events currently buffered in "
                "the process-wide ring").set_function(self.depth)
        self._dropped_handle = r.counter(
            EVENTS_DROPPED, "Structured events overwritten by the "
            "bounded ring (oldest-first)").labels()


_global_log = EventLog()


def global_event_log() -> EventLog:
    """The process-wide default log every subsystem emits into."""
    return _global_log


def emit(category: str, name: str, **attrs) -> Optional[Event]:
    """``global_event_log().emit(...)`` — the one-liner hot paths use."""
    return _global_log.emit(category, name, **attrs)


def declare_event_series(registry: Optional[MetricsRegistry] = None) -> None:
    """Declare the global log's depth/dropped series so a scrape taken
    before the first event already shows the schema."""
    _global_log.declare_series(registry)
