"""Unified telemetry subsystem.

One shared model for everything the stack observes:

    registry (metrics.py)  <-  spans (tracing.py)
                           <-  device/runtime gauges + recompile watcher
                               (runtime.py)
                           <-  fit loops / MetricsListener (listener.py)
                           <-  ParallelWrapper TrainingStats phases
    registry  ->  GET /metrics on UIServer (Prometheus text exposition)
              ->  JSONL sink / bench.py record snapshots (exporters.py)

`ensure_started()` is the one switch: idempotent, called by the fit loops
and bench drivers, it installs the jit-recompile watcher and declares the
default span series so a scrape taken before the first iteration already
shows the full schema.
"""

from __future__ import annotations

import threading

from deeplearning4j_tpu.monitoring.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, global_registry)
from deeplearning4j_tpu.monitoring.events import (  # noqa: F401
    Event, EventLog, emit, events_enabled, global_event_log,
    set_events_enabled)
from deeplearning4j_tpu.monitoring.tracing import (  # noqa: F401
    current_path, declare_default_spans, is_enabled, phase_detail,
    record_span, set_enabled, set_phase_detail, span)
from deeplearning4j_tpu.monitoring.exporters import (  # noqa: F401
    CONTENT_TYPE, JsonlSink, metrics_snapshot, render_prometheus)
from deeplearning4j_tpu.monitoring.listener import (  # noqa: F401
    MetricsListener, maybe_record_fit_iteration, record_fit_iteration)

_started = False
_start_lock = threading.Lock()


def ensure_started() -> None:
    """Idempotently turn on the process-wide default telemetry: the
    recompile watcher and the pre-declared span series."""
    global _started
    if _started:
        return
    with _start_lock:
        if _started:
            return
        from deeplearning4j_tpu.monitoring import runtime
        runtime.install_recompile_watcher()
        declare_default_spans()
        # checkpoint durability series (resilience/durable.py): declared
        # up front so a scrape taken before the first save shows the
        # full schema alongside the span series
        from deeplearning4j_tpu.resilience.durable import (
            declare_checkpoint_series)
        declare_checkpoint_series()
        # elastic membership series (resilience/elastic.py): a scrape on
        # a never-re-meshed fleet still shows generation/member gauges
        from deeplearning4j_tpu.resilience.elastic import (
            declare_elastic_series)
        declare_elastic_series()
        # structured-event series (events.py): the ring depth gauge and
        # dropped counter render before the first event fires
        from deeplearning4j_tpu.monitoring.events import (
            declare_event_series)
        declare_event_series()
        _started = True
