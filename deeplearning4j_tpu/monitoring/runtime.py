"""Device/runtime gauges and the jit-recompilation watcher.

Three signal families, all landing in the shared registry:

- per-device HBM from ``device.memory_stats()`` (bytes_in_use /
  peak_bytes_in_use / bytes_limit) — the peak gauge is the HBM
  high-water mark the bench records care about;
- host RSS, reusing ``ui/stats._current_rss_mb``;
- ``jax.jit`` cache misses counted PER FUNCTION NAME, so a per-iteration
  retrace (shape churn, stale jit key) shows up as a climbing
  ``dl4jtpu_jit_compiles_total{fn=...}`` instead of a silent 10x slowdown.

The recompile watcher taps the DEBUG-level "Compiling <fn> ..." records
that jax._src.interpreters.pxla logs on every tracing-cache miss. The
handler is non-propagating so enabling DEBUG on that logger does not spray
compile logs to the user's handlers; records at WARNING+ (the
``jax_log_compiles=True`` case) are forwarded upstream unchanged.

Everything here degrades gracefully: no jax import at module load, no
backend initialization ever (a scrape must never be the thing that first
touches — and hangs on — the accelerator; see ui/server.py's same guard).
"""

from __future__ import annotations

import logging
import re
import sys
import threading
from typing import Optional

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

COMPILE_COUNTER = "dl4jtpu_jit_compiles_total"
COMPILE_SECONDS = "dl4jtpu_jit_compile_seconds"

_JAX_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_RE = re.compile(r"^Compiling ([^\s]+) ")


def _backend_initialized() -> bool:
    """True only if a jax backend ALREADY exists — never triggers init
    (the tunneled TPU platform hangs rather than erroring when down)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb
        return bool(getattr(xb, "_backends", None))
    except Exception:  # noqa: BLE001 — private API moved: skip gauges
        return False


def update_host_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    from deeplearning4j_tpu.ui.stats import _current_rss_mb
    rss = _current_rss_mb()
    if rss is not None:
        r = registry or global_registry()
        r.gauge("dl4jtpu_host_rss_mb",
                "Host resident set size (MB)").set(rss)


def update_device_gauges(registry: Optional[MetricsRegistry] = None) -> None:
    if not _backend_initialized():
        return
    import jax
    r = registry or global_registry()
    in_use = r.gauge("dl4jtpu_device_bytes_in_use",
                     "Device memory currently allocated", ("device",))
    peak = r.gauge("dl4jtpu_device_peak_bytes_in_use",
                   "Device memory high-water mark", ("device",))
    limit = r.gauge("dl4jtpu_device_bytes_limit",
                    "Device memory capacity", ("device",))
    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 — backend died under us
        return
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — CPU backends return None/raise
            ms = None
        if not ms:
            continue
        name = f"{d.platform}:{d.id}"
        for key, gauge in (("bytes_in_use", in_use),
                           ("peak_bytes_in_use", peak),
                           ("bytes_limit", limit)):
            if key in ms:
                gauge.set(float(ms[key]), device=name)


def refresh(registry: Optional[MetricsRegistry] = None) -> None:
    """Bring point-in-time gauges current (called on every scrape)."""
    update_host_gauges(registry)
    update_device_gauges(registry)


class RecompileWatcher(logging.Handler):
    """Counts jax.jit tracing-cache misses per function name."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        super().__init__(level=logging.DEBUG)
        self._registry = registry or global_registry()
        self._prev_level: Optional[int] = None
        self._prev_propagate: Optional[bool] = None
        self._installed = False

    def counter(self):
        return self._registry.counter(
            COMPILE_COUNTER,
            "jax.jit tracing-cache misses (compiles) per function name",
            ("fn",))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
            m = _COMPILE_RE.match(msg)
            if m:
                self.counter().inc(fn=m.group(1))
            # keep jax_log_compiles=True user-visible despite propagate=False
            if record.levelno >= logging.WARNING and self._prev_propagate:
                logging.getLogger("jax").handle(record)
        except Exception:  # noqa: BLE001 — a watcher must never break a compile
            pass

    def install(self) -> "RecompileWatcher":
        if self._installed:
            return self
        self.counter()  # declare the series before the first compile
        lg = logging.getLogger(_JAX_COMPILE_LOGGER)
        self._prev_level = lg.level
        self._prev_propagate = lg.propagate
        lg.addHandler(self)
        lg.setLevel(logging.DEBUG)
        lg.propagate = False
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        lg = logging.getLogger(_JAX_COMPILE_LOGGER)
        lg.removeHandler(self)
        lg.setLevel(self._prev_level)
        lg.propagate = self._prev_propagate
        self._installed = False


_default_watcher: Optional[RecompileWatcher] = None
_duration_listener_registered = False
_lock = threading.Lock()


def _register_compile_duration_listener(
        registry: Optional[MetricsRegistry] = None) -> None:
    """Route backend-compile durations into a histogram. jax.monitoring
    offers no per-listener unregister, so this is once-per-process —
    the first installer's registry wins, matching the default-watcher
    rule in install_recompile_watcher."""
    global _duration_listener_registered
    if _duration_listener_registered:
        return
    try:
        import jax.monitoring as jm
    except Exception:  # noqa: BLE001 — no jax here
        return
    hist = (registry or global_registry()).histogram(
        COMPILE_SECONDS, "XLA backend compile durations")

    def _on_duration(event: str, duration: float, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            hist.observe(duration)

    jm.register_event_duration_secs_listener(_on_duration)
    _duration_listener_registered = True


def install_recompile_watcher(
        registry: Optional[MetricsRegistry] = None) -> RecompileWatcher:
    """Idempotent process-wide default watcher (fit loops and bench
    drivers call this; the first call wins)."""
    global _default_watcher
    with _lock:
        if _default_watcher is None:
            _default_watcher = RecompileWatcher(registry).install()
            _register_compile_duration_listener(registry)
        return _default_watcher
