"""Process-wide metrics registry: Counter / Gauge / Histogram primitives.

SURVEY §5 "Tracing/profiling": the reference stack observes training only
through ad-hoc listener timing (PerformanceListener, BaseStatsListener
sections) with no shared model. This module is the shared model: a
thread-safe registry of labeled metrics that every layer of the stack
(fit loops, parallel wrapper, UI server, bench drivers) publishes into,
and that exporters.py renders as Prometheus text exposition or JSONL.

Deliberately jax-free: bench.py must be able to snapshot the registry on
its failure paths (tpu-unavailable) where the accelerator runtime never
came up. Device-level gauges live in runtime.py.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# seconds-oriented (spans, compile times); Prometheus-client's defaults
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Metric:
    """Base labeled metric. One instance per metric NAME; per-label-value
    children are created lazily on first touch (prometheus-client model).
    All mutation happens under the owning registry's lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _child(self, labels: Dict[str, Any]):
        key = self._key(labels)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = self._new_child()
        return child

    def _new_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, **labels) -> "_Handle":
        """Get (creating if needed) the child for a label combination —
        creating it declares the series so it renders even with no data."""
        with self._lock:
            self._child(labels)
        return _Handle(self, labels)

    def label_values(self) -> List[Tuple[str, ...]]:
        with self._lock:
            return sorted(self._children)


class _Handle:
    """Bound (metric, labels) pair returned by .labels(**kw)."""

    __slots__ = ("_metric", "_labels")

    def __init__(self, metric: Metric, labels: Dict[str, Any]):
        self._metric = metric
        self._labels = labels

    def __getattr__(self, item):
        fn = getattr(self._metric, item)

        def bound(*args, **kw):
            return fn(*args, **self._labels, **kw)
        return bound


class Counter(Metric):
    """Monotonically increasing counter."""

    kind = "counter"

    def _new_child(self) -> List[float]:
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            self._child(labels)[0] += amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._child(labels)[0]

    def total(self) -> float:
        """Sum over every label combination."""
        with self._lock:
            return sum(c[0] for c in self._children.values())


class Gauge(Metric):
    """Point-in-time value; also supports scrape-time callbacks."""

    kind = "gauge"

    def _new_child(self) -> List[Any]:
        return [0.0]

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._child(labels)[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            child = self._child(labels)
            if callable(child[0]):
                raise ValueError(f"{self.name}: callback gauge is read-only")
            child[0] += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float], **labels) -> None:
        """Evaluate `fn` at collection time (e.g. RSS, queue depth)."""
        with self._lock:
            self._child(labels)[0] = fn

    def value(self, **labels) -> float:
        with self._lock:
            v = self._child(labels)[0]
        return float(v()) if callable(v) else float(v)


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{self.name}: need at least one bucket")
        self.buckets: Tuple[float, ...] = tuple(bs)

    def _new_child(self):
        # [per-bucket counts..., +Inf count], sum, count
        return {"counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "n": 0}

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        with self._lock:
            child = self._child(labels)
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if value <= b:
                    i = j
                    break
            child["counts"][i] += 1
            child["sum"] += value
            child["n"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            return self._child(labels)["n"]

    def sum(self, **labels) -> float:
        with self._lock:
            return self._child(labels)["sum"]


class MetricsRegistry:
    """Thread-safe get-or-create registry of named metrics.

    `counter`/`gauge`/`histogram` are idempotent accessors: the first call
    creates the metric, later calls return it (and type/label mismatches
    raise instead of silently aliasing two meanings onto one name)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames,
                                              self._lock, **kw)
                return m
            if not isinstance(m, cls):
                raise ValueError(f"{name} already registered as {m.kind}")
            if m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"{name} already registered with labels {m.labelnames}")
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets)
        if h.buckets != tuple(sorted(float(b) for b in buckets)):
            raise ValueError(
                f"{name} already registered with buckets {h.buckets}")
        return h

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def collect(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # ------------------------------------------------------------------
    # snapshots
    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Full structured dump: {name: {type, help, samples: [...]}}."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                samples = []
                for key in sorted(m._children):
                    labels = dict(zip(m.labelnames, key))
                    child = m._children[key]
                    if m.kind == "histogram":
                        samples.append({"labels": labels,
                                        "count": child["n"],
                                        "sum": child["sum"]})
                    else:
                        v = child[0]
                        if callable(v):
                            try:
                                v = float(v())
                            except Exception:  # noqa: BLE001 — scrape-safe
                                continue
                        samples.append({"labels": labels, "value": v})
                out[name] = {"type": m.kind, "help": m.help,
                             "samples": samples}
        return out

    def snapshot_compact(self) -> Dict[str, Any]:
        """Flat one-JSON-object summary for bench records: counters/gauges
        as `name{k=v}` -> value, histograms -> {count, sum, mean}."""
        out: Dict[str, Any] = {}
        for name, m in self.snapshot().items():
            for s in m["samples"]:
                key = compact_key(name, s["labels"])
                if m["type"] == "histogram":
                    n = s["count"]
                    if n:  # empty series add noise, not information, here
                        out[key] = {"count": n, "sum": round(s["sum"], 6),
                                    "mean": round(s["sum"] / n, 6)}
                else:
                    out[key] = s["value"]
        return out


def compact_key(name: str, labels: Dict[str, Any]) -> str:
    """`name{k=v,...}` key used by the compact snapshot formats."""
    if not labels:
        return name
    return name + "{" + ",".join(
        f"{k}={labels[k]}" for k in sorted(labels)) + "}"


_global = MetricsRegistry()


def global_registry() -> MetricsRegistry:
    """The default process-wide registry (exported at /metrics)."""
    return _global
