"""Nestable span tracing bridged to both the metrics registry and XPlane.

    with span("forward"):
        ...

records the wall-clock duration into the `dl4jtpu_span_seconds{span=...}`
histogram of the global registry AND emits a `jax.profiler.TraceAnnotation`
so the same region lines up with XPlane traces captured by
`optimize.profiler.ProfilerListener` (TensorBoard/xprof shows the span as
a named host-side slice inside the trace window).

Spans nest via a thread-local stack (`current_path()` returns e.g.
"iteration/forward"); the histogram label stays the LEAF name so series
cardinality is bounded by the set of span names, not call paths.

`set_enabled(False)` turns spans into no-ops (for overhead-sensitive
loops); `set_phase_detail(True)` switches the fit loops from the single
fused train step (span "step") to split forward/backward/update steps so
the per-phase histograms carry real device timings — see
MultiLayerNetwork._get_phase_steps for the cost tradeoff.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)

SPAN_HISTOGRAM = "dl4jtpu_span_seconds"
SPAN_ERRORS = "dl4jtpu_span_errors_total"

#: the phase names the fit loops emit; declared eagerly so the /metrics
#: exposition always carries all per-phase series (etl/forward/backward/
#: update populate per the phase-detail mode, "step" is the fused step)
DEFAULT_SPANS = ("etl", "forward", "backward", "update", "step", "listener")

_tls = threading.local()
_enabled = True
_phase_detail = os.environ.get(
    "DL4JTPU_PHASE_DETAIL", "0").strip().lower() not in (
    "0", "", "false", "no", "off")

# jax.profiler.TraceAnnotation, resolved lazily: the metrics side of a
# span must work in processes where jax never imported (bench failure
# paths). None = unresolved, False = unavailable.
_annotation_cls = None


def _get_annotation_cls():
    global _annotation_cls
    if _annotation_cls is None:
        try:
            from jax.profiler import TraceAnnotation
            _annotation_cls = TraceAnnotation
        except Exception:  # noqa: BLE001 — no jax: spans still time
            _annotation_cls = False
    return _annotation_cls


def set_enabled(flag: bool) -> None:
    global _enabled
    _enabled = bool(flag)


def is_enabled() -> bool:
    return _enabled


def set_phase_detail(flag: bool) -> None:
    """True: fit loops run split forward/backward/update jitted steps so
    those spans measure real device time (3 dispatches, residuals
    materialized at the seams). False (default): the single fused step
    keeps maximum XLA fusion and records under span "step"."""
    global _phase_detail
    _phase_detail = bool(flag)


def phase_detail() -> bool:
    return _phase_detail


def current_path() -> str:
    """Slash-joined stack of open spans on this thread ("" outside any)."""
    return "/".join(getattr(_tls, "stack", ()))


def span_histogram(registry: Optional[MetricsRegistry] = None):
    r = registry or global_registry()
    return r.histogram(
        SPAN_HISTOGRAM,
        "Wall-clock seconds of named training-loop spans "
        "(host-side; aligns with XPlane TraceAnnotations)", ("span",))


def record_span(name: str, seconds: float,
                registry: Optional[MetricsRegistry] = None) -> None:
    """Directly record a span observation (used by TrainingStats and any
    timer that measured the interval itself)."""
    span_histogram(registry).observe(seconds, span=name)


def declare_default_spans(registry: Optional[MetricsRegistry] = None) -> None:
    h = span_histogram(registry)
    for name in DEFAULT_SPANS:
        h.labels(span=name)


class span:
    """Context manager: time a region into the registry + XPlane."""

    __slots__ = ("name", "registry", "_t0", "_ann")

    def __init__(self, name: str,
                 registry: Optional[MetricsRegistry] = None):
        self.name = name
        self.registry = registry

    def __enter__(self):
        if not _enabled:
            self._t0 = None
            return self
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.name)
        self._ann = None
        cls = _get_annotation_cls()
        if cls:
            try:
                self._ann = cls(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 — annotation is best-effort
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
        _tls.stack.pop()
        r = self.registry or global_registry()
        span_histogram(r).observe(dt, span=self.name)
        if exc_type is not None:
            r.counter(SPAN_ERRORS,
                      "Spans that exited via an exception",
                      ("span",)).inc(span=self.name)
        return False
