"""MetricsListener + the default fit-loop telemetry hook.

The fit loops publish score/throughput/iteration counters into the global
registry by default via `maybe_record_fit_iteration` — zero configuration,
near-zero cost (a handful of locked float adds per batch). Attaching a
`MetricsListener` explicitly takes over that publishing (the auto-hook
steps aside so nothing double-counts), which is how you point a model at
a NON-global registry or change the cadence.
"""

from __future__ import annotations

import math
import time
from typing import Optional

from deeplearning4j_tpu.monitoring.metrics import (
    MetricsRegistry, global_registry)
from deeplearning4j_tpu.optimize.listeners import TrainingListener

SCORE_GAUGE = "dl4jtpu_score"
ITERATIONS = "dl4jtpu_iterations_total"
EXAMPLES = "dl4jtpu_examples_total"
SAMPLES_PER_SEC = "dl4jtpu_samples_per_sec"
BATCHES_PER_SEC = "dl4jtpu_batches_per_sec"
EPOCHS = "dl4jtpu_epochs_total"


def record_fit_iteration(model, n_examples: int, score: float,
                         seconds: Optional[float] = None,
                         registry: Optional[MetricsRegistry] = None,
                         n_batches: int = 1) -> None:
    """Publish one training-iteration interval's telemetry (`n_batches`
    iterations and `n_examples` examples over `seconds` wall-clock)."""
    r = registry or global_registry()
    name = type(model).__name__
    r.counter(ITERATIONS, "Completed training iterations",
              ("model",)).inc(n_batches, model=name)
    if n_examples:
        r.counter(EXAMPLES, "Examples consumed by training",
                  ("model",)).inc(n_examples, model=name)
    if score is not None and not math.isnan(score):
        r.gauge(SCORE_GAUGE, "Latest training loss/score",
                ("model",)).set(float(score), model=name)
    if seconds is not None and seconds > 0:
        r.gauge(BATCHES_PER_SEC, "Training iterations per second",
                ("model",)).set(n_batches / seconds, model=name)
        if n_examples:
            r.gauge(SAMPLES_PER_SEC, "Training examples per second",
                    ("model",)).set(n_examples / seconds, model=name)


#: cadence (in iterations) of score-gauge publication by the auto-hook.
#: Reading `model.score_value` forces a device->host sync, so doing it
#: every batch would serialize dispatch (tpulint: host-sync-in-hot-loop);
#: counters/throughput stay per-batch (host floats, free), the score
#: lands every Nth iteration and once more at the end of fit.
_SCORE_PUBLISH_EVERY = 25


def set_score_publish_interval(n: int) -> int:
    """Set the auto-hook's score cadence; returns the previous value."""
    global _SCORE_PUBLISH_EVERY
    prev, _SCORE_PUBLISH_EVERY = _SCORE_PUBLISH_EVERY, max(1, int(n))
    return prev


def maybe_record_fit_iteration(model, n_examples: int,
                               seconds: Optional[float],
                               n_batches: int = 1) -> None:
    """Default fit-loop hook: records into the global registry unless the
    model carries an explicit MetricsListener (which then owns publishing).
    The score is read (= synced) only on the publish cadence; other
    gauges cost nothing."""
    if any(isinstance(l, MetricsListener)
           for l in getattr(model, "listeners", ())):
        return
    it = getattr(model, "iteration_count", 0)
    score = None
    if it == 1 or it % _SCORE_PUBLISH_EVERY == 0:
        score = getattr(model, "score_value", None)
    record_fit_iteration(model, n_examples, score, seconds,
                         n_batches=n_batches)


def finalize_fit_telemetry(model) -> None:
    """End-of-fit barrier: ONE deliberate host sync after the last batch.

    Blocks on the final params (so deferred dispatch errors surface
    inside fit, not at some later read) and publishes the terminal score
    gauge that the lazy per-batch path skipped. This is the 'final batch'
    sync the fit loops are allowed to keep."""
    import jax

    params = getattr(model, "params", None)
    if params is not None:
        jax.block_until_ready(params)
    # settle the non-finite sentinel's pending flags (resilience/): the
    # bad/skipped-step counters must be current once fit returns
    from deeplearning4j_tpu.resilience.sentinel import flush_accounting
    flush_accounting(model)
    if any(isinstance(l, MetricsListener)
           for l in getattr(model, "listeners", ())):
        return  # explicit listener owns publishing
    # terminal score gauge via the shared publish path (0 batches/examples:
    # only the nan-guarded score gauge actually lands)
    record_fit_iteration(model, 0, getattr(model, "score_value", None),
                         None, n_batches=0)


class MetricsListener(TrainingListener):
    """TrainingListener that publishes score, samples/sec and batches/sec
    into a metrics registry (the telemetry-era PerformanceListener)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 frequency: int = 1):
        self.registry = registry or global_registry()
        self.frequency = max(1, frequency)
        self._samples = 0
        self._batches = 0
        self._last_time: Optional[float] = None

    def record_batch(self, num_examples: int) -> None:
        self._samples += num_examples

    def iteration_done(self, model, iteration: int, score: float) -> None:
        self._batches += 1
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        dt = None if self._last_time is None else now - self._last_time
        self._last_time = now
        record_fit_iteration(model, self._samples, score, dt,
                             self.registry, n_batches=self._batches)
        self._samples = 0
        self._batches = 0

    def on_epoch_end(self, model, epoch: int) -> None:
        self.registry.counter(EPOCHS, "Completed training epochs",
                              ("model",)).inc(model=type(model).__name__)
