"""Registry exporters: Prometheus text exposition and a JSONL file sink.

`render_prometheus()` produces text-exposition-format 0.0.4 (the format
every Prometheus/VictoriaMetrics/Grafana-agent scraper speaks); the
UIServer serves it at GET /metrics. `JsonlSink` appends one JSON object
per call — the same shape bench.py embeds in its one-line records, so a
long run can stream periodic snapshots next to its result line.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.monitoring.metrics import (
    Histogram, MetricsRegistry, compact_key, global_registry)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def render_prometheus(registry: Optional[MetricsRegistry] = None,
                      refresh_runtime: bool = True) -> str:
    """Render the registry in Prometheus text exposition format."""
    r = registry or global_registry()
    if refresh_runtime:
        # bring RSS/HBM gauges current at scrape time — bounded, because
        # memory_stats() over a dead TPU tunnel hangs rather than raising
        # and a scrape (or the README's render_prometheus() call) must
        # never block on it; a late-finishing refresh just lands in the
        # next scrape (never inits a backend — runtime._backend_initialized)
        refresh_runtime_bounded(registry=r)
    lines = []
    for m in r.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        with m._lock:
            # copy child state under the lock: a concurrent observe()
            # must not tear bucket counts vs _sum/_count mid-render
            children = [
                (dict(zip(m.labelnames, key)),
                 dict(m._children[key], counts=list(m._children[key]["counts"]))
                 if isinstance(m, Histogram) else list(m._children[key]))
                for key in sorted(m._children)]
        if isinstance(m, Histogram):
            for labels, child in children:
                cum = 0
                for i, b in enumerate(m.buckets):
                    cum += child["counts"][i]
                    le = 'le="%s"' % _fmt_value(b)
                    lines.append(f"{m.name}_bucket"
                                 f"{_fmt_labels(labels, le)} {cum}")
                cum += child["counts"][-1]
                le = 'le="+Inf"'
                lines.append(f"{m.name}_bucket"
                             f"{_fmt_labels(labels, le)} {cum}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(child['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)}"
                             f" {child['n']}")
        else:
            for labels, child in children:
                v = child[0]
                if callable(v):
                    try:
                        v = float(v())
                    except Exception:  # noqa: BLE001 — scrape must not 500
                        continue
                lines.append(f"{m.name}{_fmt_labels(labels)}"
                             f" {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


class JsonlSink:
    """Append registry snapshots to a JSONL file, one object per line."""

    def __init__(self, path: str,
                 registry: Optional[MetricsRegistry] = None,
                 compact: bool = True):
        self.path = path
        self.registry = registry or global_registry()
        self.compact = compact

    def write_snapshot(self, extra: Optional[Dict[str, Any]] = None) -> None:
        snap = (self.registry.snapshot_compact() if self.compact
                else self.registry.snapshot())
        rec = {"timestamp": time.time(), "metrics": snap}
        if extra:
            rec.update(extra)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def refresh_runtime_bounded(timeout: float = 5.0,
                            registry: Optional[MetricsRegistry] = None
                            ) -> None:
    """Refresh runtime gauges on a daemon thread, waiting at most
    ``timeout``. ``memory_stats()`` over a dead TPU tunnel HANGS rather
    than raising, and no caller on a result-line path can afford that:
    a stuck refresh must cost at most the timeout, never the record.
    The registry is thread-safe, so a late-finishing refresh just
    updates gauges after the caller's snapshot was taken."""
    try:
        from deeplearning4j_tpu.monitoring import runtime

        def _refresh():
            try:
                runtime.refresh(registry)
            except Exception:  # noqa: BLE001 — gauges are best-effort
                pass

        t = threading.Thread(target=_refresh, daemon=True,
                             name="metrics-runtime-refresh")
        t.start()
        t.join(timeout)
    except Exception:  # noqa: BLE001 — gauges are best-effort
        pass


def metrics_snapshot(refresh_timeout: float = 5.0) -> Dict[str, Any]:
    """Compact global-registry snapshot for embedding in bench records.
    Refreshes runtime gauges first (bounded, guarded: no backend init)
    and never raises — the snapshot must survive the tpu-unavailable
    paths."""
    try:
        refresh_runtime_bounded(refresh_timeout)
        return global_registry().snapshot_compact()
    except Exception:  # noqa: BLE001 — a bench record beats a traceback
        return {}


def snapshot_delta_compact(prev: Optional[Dict[str, Any]],
                           cur: Dict[str, Any]) -> Dict[str, Any]:
    """Compact rendering of ``cur`` minus ``prev`` (both full
    ``MetricsRegistry.snapshot()`` dicts): counters and histograms become
    the increment since ``prev`` (zero-increment series are dropped as
    noise), gauges keep their point-in-time value. bench_all stamps one
    of these per record so the Nth bench's "metrics" field carries only
    that bench's own spans and compile counts, not the cumulative totals
    of every bench the process ran before it."""
    prev_samples: Dict[str, Dict[str, Any]] = {}
    for name, m in (prev or {}).items():
        for s in m["samples"]:
            prev_samples[compact_key(name, s["labels"])] = s

    out: Dict[str, Any] = {}
    for name, m in cur.items():
        for s in m["samples"]:
            key = compact_key(name, s["labels"])
            p = prev_samples.get(key)
            if m["type"] == "histogram":
                n = s["count"] - (p["count"] if p else 0)
                if n > 0:
                    total = s["sum"] - (p["sum"] if p else 0.0)
                    out[key] = {"count": n, "sum": round(total, 6),
                                "mean": round(total / n, 6)}
            elif m["type"] == "counter":
                d = s["value"] - (p["value"] if p else 0.0)
                if d:
                    out[key] = d
            else:
                out[key] = s["value"]
    return out
