"""Weight initialization.

TPU-native equivalent of the reference's WeightInit enum + WeightInitUtil
(deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/weights/WeightInit.java,
WeightInitUtil.java). Semantics follow the reference's fan-in/fan-out formulas;
the implementation is pure `jax.random` so initialization itself runs on device
and is reproducible from a single PRNG key (replacing the ref's global
Nd4j RNG seed, NeuralNetConfiguration.Builder#seed).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["init_weights", "WEIGHT_INITS"]

WEIGHT_INITS = (
    "zero",
    "ones",
    "uniform",
    "sigmoid_uniform",
    "xavier",
    "xavier_uniform",
    "xavier_fan_in",
    "xavier_legacy",
    "relu",
    "relu_uniform",
    "lecun_normal",
    "lecun_uniform",
    "normal",
    "truncated_normal",
    "var_scaling_normal_fan_in",
    "var_scaling_normal_fan_out",
    "var_scaling_normal_fan_avg",
    "var_scaling_uniform_fan_in",
    "var_scaling_uniform_fan_out",
    "var_scaling_uniform_fan_avg",
    "distribution",
    "identity",
)


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    fan_in: float,
    fan_out: float,
    scheme: str = "xavier",
    distribution: Optional[dict] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Initialize a weight array with the named scheme.

    fan_in/fan_out follow WeightInitUtil semantics: for dense [nIn, nOut]
    fan_in=nIn fan_out=nOut; for conv kernels fan_in = inChannels*kH*kW,
    fan_out = outChannels*kH*kW.
    """
    scheme = str(scheme).lower()
    shape = tuple(int(s) for s in shape)

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "uniform":
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        r = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == "xavier":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_uniform":
        s = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -s, s)
    if scheme == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "xavier_legacy":
        std = math.sqrt(1.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "relu":
        return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)
    if scheme == "relu_uniform":
        u = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -u, u)
    if scheme == "lecun_normal":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "lecun_uniform":
        b = 3.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -b, b)
    if scheme == "normal":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "truncated_normal":
        return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) / math.sqrt(fan_in)
    if scheme.startswith("var_scaling"):
        if scheme.endswith("fan_in"):
            denom = fan_in
        elif scheme.endswith("fan_out"):
            denom = fan_out
        else:
            denom = 0.5 * (fan_in + fan_out)
        if "normal" in scheme:
            return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * math.sqrt(
                1.0 / denom
            )
        lim = math.sqrt(3.0 / denom)
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "distribution":
        return _sample_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")


def _sample_distribution(key, shape, dist: dict, dtype):
    """Sample from a configured distribution (ref: nn/conf/distribution/*)."""
    kind = str(dist.get("type", "normal")).lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lower = float(dist.get("lower", -1.0))
        upper = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, lower, upper)
    if kind == "binomial":
        n = int(dist.get("trials", 1))
        p = float(dist.get("probability", 0.5))
        out = jnp.zeros(shape, dtype)
        for sub in jax.random.split(key, n):
            out = out + jax.random.bernoulli(sub, p, shape).astype(dtype)
        return out
    if kind == "constant":
        return jnp.full(shape, float(dist.get("value", 0.0)), dtype)
    if kind in ("truncated_normal", "truncatednormal"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
    raise ValueError(f"Unknown distribution type '{kind}'")
