"""Shared compute-dtype policy for MultiLayerNetwork / ComputationGraph.

One place for the two halves of the mixed-precision contract:
- `bf16_cast` — the conf.dtype="bfloat16" compute cast (params +
  activations run bf16; MXU path with fp32 accumulation, the same
  compute policy the reference's cuDNN helpers select via
  BaseCudnnHelper dataType);
- `f32_head` — public outputs (output / rnn_time_step) promote sub-f32
  floats back to f32 at the jit boundary; f32/f64 pass through
  untouched (a f64 network keeps f64 outputs).

conf.dtype is part of every jitted-step cache key (the policy is baked
into the trace — a stale compiled step would silently keep the old
precision, the same staleness rule as _STREAM_CACHE_SHARDING).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def bf16_cast(a):
    """Cast one floating array to bfloat16 (non-floats untouched)."""
    return a.astype(jnp.bfloat16) \
        if jnp.issubdtype(a.dtype, jnp.floating) else a


def bf16_cast_tree(tree):
    """bf16-cast every floating leaf of a pytree."""
    return jax.tree_util.tree_map(bf16_cast, tree)


def f32_head(a):
    """Promote a sub-f32 floating output (bf16/f16 compute) to f32 at
    the public boundary; f32/f64 (and non-floats) pass through."""
    if not jnp.issubdtype(a.dtype, jnp.floating):
        return a
    t = jnp.promote_types(a.dtype, jnp.float32)
    return a if t == a.dtype else a.astype(t)
