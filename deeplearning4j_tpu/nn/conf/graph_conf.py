"""Graph vertices + GraphBuilder for DAG networks.

TPU-native equivalent of nn/conf/graph/* and nn/graph/vertex/impl/*
(LayerVertex, MergeVertex, ElementWiseVertex, SubsetVertex, Stack/Unstack,
Scale/Shift, L2NormalizeVertex, L2Vertex, PreprocessorVertex,
rnn/LastTimeStepVertex, rnn/DuplicateToTimeSeriesVertex) and of
ComputationGraphConfiguration.GraphBuilder (addInputs/addLayer/addVertex/
setOutputs — ComputationGraphConfiguration.java GraphBuilder).

Vertices are pure functions of their input activations; autodiff handles the
reverse-topo epsilon accumulation the reference hand-writes
(ComputationGraph.calcBackpropGradients :1629).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    LayerConf,
    layer_from_dict,
    layer_to_dict,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    Preprocessor,
    preprocessor_from_dict,
    preprocessor_to_dict,
)

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_to_dict(v) -> dict:
    d = {"@class": type(v).__name__}
    for f in dataclasses.fields(v):
        val = getattr(v, f.name)
        if isinstance(val, LayerConf):
            val = layer_to_dict(val)
        elif isinstance(val, Preprocessor):
            val = preprocessor_to_dict(val)
        elif isinstance(val, tuple):
            val = list(val)
        d[f.name] = val
    return d


def vertex_from_dict(d: dict):
    d = dict(d)
    cls = VERTEX_REGISTRY[d.pop("@class")]
    names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {k: v for k, v in d.items() if k in names}
    return cls(**kwargs)


@dataclass
class GraphVertexConf:
    """Base vertex: pure function of input activation list."""

    def output_type(self, its: List[InputType]) -> InputType:
        return its[0]

    def init(self, key, its: List[InputType]):
        return {}, {}

    def apply(self, params, xs: List, state, *, train=False, rng=None, mask=None):
        raise NotImplementedError

    def output_mask(self, masks, its):
        for m in masks:
            if m is not None:
                return m
        return None


@register_vertex
@dataclass
class LayerVertex(GraphVertexConf):
    """Wraps a layer conf (+ optional preprocessor)
    (ref: nn/graph/vertex/impl/LayerVertex.java)."""

    layer: Any = None  # LayerConf | dict
    preprocessor: Any = None  # Preprocessor | dict | None

    def __post_init__(self):
        if isinstance(self.layer, dict):
            self.layer = layer_from_dict(self.layer)
        if isinstance(self.preprocessor, dict):
            self.preprocessor = preprocessor_from_dict(self.preprocessor)

    def output_type(self, its):
        it = its[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it)

    def init(self, key, its):
        it = its[0]
        if self.preprocessor is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.init(key, it)

    @property
    def supports_streaming(self):
        return getattr(self.layer, "supports_streaming", False)

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None,
              **extra):
        x = xs[0]
        if self.preprocessor is not None:
            x = self.preprocessor.apply(x, mask)
        return self.layer.apply(params, x, state, train=train, rng=rng,
                                mask=mask, **extra)

    def output_mask(self, masks, its):
        m = masks[0] if masks else None
        it = its[0]
        if self.preprocessor is not None:
            m = self.preprocessor.output_mask(m, it)
            it = self.preprocessor.output_type(it)
        return self.layer.output_mask(m, it)


@register_vertex
@dataclass
class CrossAttentionVertex(GraphVertexConf):
    """Multi-head cross attention: queries from input 0, keys/values from
    input 1 (both RNN-format [N,F,T]) — the encoder-decoder bridge the
    2017 reference predates. Scores/outputs run through
    blockwise_attention (Pallas flash kernel on TPU — its query/key
    lengths are independent, so decoder and encoder lengths may differ);
    input 1's feature mask masks encoder padding KEYS.

    Params: Wq [Fq,E]+bq from input 0; Wk/Wv [Fkv,E]+bk/bv from input 1;
    Wo [E,E]+bo. `n_out` defaults to input 0's size; `n_heads` must
    divide it."""

    n_out: Optional[int] = None
    n_heads: int = 4
    block_size: int = 512
    weight_init: str = "xavier"

    def output_type(self, its):
        if any(it.kind != "rnn" for it in its[:2]):
            raise ValueError("CrossAttentionVertex needs two RNN inputs")
        return InputType.recurrent(self.n_out or its[0].size,
                                   its[0].timesteps)

    def init(self, key, its):
        if len(its) < 2:
            raise ValueError("CrossAttentionVertex needs two inputs "
                             "(queries, memory)")
        from deeplearning4j_tpu.nn.weights import init_weights
        E = self.n_out or its[0].size
        if E % self.n_heads:
            raise ValueError(f"n_out {E} not divisible by n_heads "
                             f"{self.n_heads}")
        self.n_out = E
        fq, fkv = its[0].size, its[1].size
        keys = jax.random.split(key, 4)
        p = {}
        for i, (name, f_in) in enumerate((("q", fq), ("k", fkv),
                                          ("v", fkv), ("o", E))):
            p["W" + name] = init_weights(keys[i], (f_in, E), f_in, E,
                                         self.weight_init, None)
            p["b" + name] = jnp.zeros((E,), jnp.float32)
        return p, {}

    #: graph passes the full per-input mask list (encoder mask = keys)
    wants_all_masks = True

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        # intentionally parallel to SelfAttentionLayer.apply's project/
        # split/attend/merge sequence (nn/conf/layers.py) — kept separate
        # because the layer variant carries GQA/rope/streaming/window
        # behavior this two-input vertex deliberately does not
        from deeplearning4j_tpu.parallel.sequence import blockwise_attention
        xq, xkv = xs[0], xs[1]
        kv_mask = mask[1] if isinstance(mask, (list, tuple)) and \
            len(mask) > 1 else None
        n, _, tq = xq.shape
        tk = xkv.shape[2]
        h = self.n_heads
        d = self.n_out // h

        def proj(x, t, name):
            y = jnp.transpose(x, (0, 2, 1)) @ params["W" + name] + \
                params["b" + name]
            return y.reshape(n, t, h, d).transpose(0, 2, 1, 3)

        q = proj(xq, tq, "q")
        k, v = proj(xkv, tk, "k"), proj(xkv, tk, "v")
        o = blockwise_attention(q, k, v, causal=False,
                                block_size=self.block_size,
                                key_mask=kv_mask)
        o = o.transpose(0, 2, 1, 3).reshape(n, tq, self.n_out)
        o = o @ params["Wo"] + params["bo"]
        return jnp.transpose(o, (0, 2, 1)), state

    def output_mask(self, masks, its):
        return masks[0] if masks else None   # query-side mask propagates


@register_vertex
@dataclass
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature axis (axis 1 for FF/CNN/RNN — DL4J
    merges on depth/features; ref: vertex/impl/MergeVertex.java). Under
    internal NHWC, 4-D inputs carry channels on the last axis."""

    data_format: str = "NCHW"

    def output_type(self, its):
        first = its[0]
        if first.kind == "cnn":
            ch = sum(it.channels for it in its)
            return InputType.convolutional(first.height, first.width, ch)
        if first.kind == "rnn":
            return InputType.recurrent(sum(it.size for it in its), first.timesteps)
        return InputType.feed_forward(sum(it.flat_size() for it in its))

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        axis = 3 if (self.data_format == "NHWC" and xs[0].ndim == 4) else 1
        return jnp.concatenate(xs, axis=axis), state


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertexConf):
    """Element-wise op across inputs: Add/Subtract/Product/Average/Max
    (ref: vertex/impl/ElementWiseVertex.java)."""

    op: str = "add"

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        op = self.op.lower()
        y = xs[0]
        if op == "add":
            for x in xs[1:]:
                y = y + x
        elif op in ("subtract", "sub"):
            y = xs[0] - xs[1]
        elif op in ("product", "mul"):
            for x in xs[1:]:
                y = y * x
        elif op in ("average", "avg"):
            y = sum(xs) / float(len(xs))
        elif op == "max":
            for x in xs[1:]:
                y = jnp.maximum(y, x)
        else:
            raise ValueError(f"unknown elementwise op {self.op}")
        return y, state


@register_vertex
@dataclass
class SubsetVertex(GraphVertexConf):
    """Take features [from, to] inclusive (ref: vertex/impl/SubsetVertex.java)."""

    from_index: int = 0
    to_index: int = 0
    data_format: str = "NCHW"  # feature axis of 4-D input moves under NHWC

    def output_type(self, its):
        n = self.to_index - self.from_index + 1
        it = its[0]
        if it.kind == "rnn":
            return InputType.recurrent(n, it.timesteps)
        if it.kind == "cnn":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        x = xs[0]
        sl = slice(self.from_index, self.to_index + 1)
        if self.data_format == "NHWC" and x.ndim == 4:
            return x[..., sl], state
        return x[:, sl], state


@register_vertex
@dataclass
class StackVertex(GraphVertexConf):
    """Stack inputs along batch axis (ref: vertex/impl/StackVertex.java)."""

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        return jnp.concatenate(xs, axis=0), state


@register_vertex
@dataclass
class UnstackVertex(GraphVertexConf):
    """Take stack slice `from_index` of `stack_size` (ref: UnstackVertex.java)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step], state


@register_vertex
@dataclass
class ScaleVertex(GraphVertexConf):
    """Multiply by scalar (ref: ScaleVertex.java)."""

    scale: float = 1.0

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        return xs[0] * self.scale, state


@register_vertex
@dataclass
class ShiftVertex(GraphVertexConf):
    """Add scalar (ref: ShiftVertex.java)."""

    shift: float = 0.0

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        return xs[0] + self.shift, state


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertexConf):
    """Normalize each example to unit L2 norm (ref: L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        x = xs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / n, state


@register_vertex
@dataclass
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two inputs (ref: L2Vertex.java)."""

    eps: float = 1e-8

    def output_type(self, its):
        return InputType.feed_forward(1)

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        a, b = xs[0], xs[1]
        axes = tuple(range(1, a.ndim))
        d = jnp.sqrt(jnp.sum((a - b) ** 2, axis=axes, keepdims=False) + self.eps)
        return d[:, None], state


@register_vertex
@dataclass
class PreprocessorVertex(GraphVertexConf):
    """Standalone preprocessor vertex (ref: PreprocessorVertex.java)."""

    preprocessor: Any = None

    def __post_init__(self):
        if isinstance(self.preprocessor, dict):
            self.preprocessor = preprocessor_from_dict(self.preprocessor)

    def output_type(self, its):
        return self.preprocessor.output_type(its[0])

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        return self.preprocessor.apply(xs[0], mask), state


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertexConf):
    """[N,C,T] -> [N,C] at the last unmasked step
    (ref: rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None

    def output_type(self, its):
        return InputType.feed_forward(its[0].size)

    def output_mask(self, masks, its):
        return None

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        x = xs[0]
        if mask is None:
            return x[:, :, -1], state
        idx = jnp.clip(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0,
                       x.shape[2] - 1)
        return jnp.take_along_axis(x, idx[:, None, None], axis=2)[:, :, 0], state


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """[N,C] -> [N,C,T], duplicating across time
    (ref: rnn/DuplicateToTimeSeriesVertex.java). T is taken from a reference
    RNN input at apply time via the `timesteps` attribute set by the graph."""

    ts_input: Optional[str] = None
    timesteps: int = 1

    def output_type(self, its):
        return InputType.recurrent(its[0].flat_size(), self.timesteps)

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        x = xs[0]
        t = self.timesteps
        if len(xs) > 1 and xs[1].ndim == 3:  # reference sequence provided
            t = xs[1].shape[2]
        return jnp.repeat(x[:, :, None], t, axis=2), state


@register_vertex
@dataclass
class PoolHelperVertex(GraphVertexConf):
    """Strip first row/col of a CNN activation (GoogLeNet compat shim;
    ref: PoolHelperVertex.java)."""

    data_format: str = "NCHW"

    def output_type(self, its):
        it = its[0]
        return InputType.convolutional(it.height - 1, it.width - 1, it.channels)

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        if self.data_format == "NHWC":
            return xs[0][:, 1:, 1:, :], state
        return xs[0][:, :, 1:, 1:], state


@register_vertex
@dataclass
class ReshapeVertex(GraphVertexConf):
    """Reshape to a fixed per-example shape."""

    shape: Sequence[int] = ()

    def apply(self, params, xs, state, *, train=False, rng=None, mask=None):
        return xs[0].reshape((xs[0].shape[0],) + tuple(self.shape)), state


class GraphBuilder:
    """Fluent DAG builder (ref: ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, parent=None):
        from deeplearning4j_tpu.nn.conf.network import (
            ComputationGraphConfiguration, NeuralNetConfiguration)
        if parent is None:
            # reference spelling allows standalone
            # ComputationGraphConfiguration.GraphBuilder() with default
            # global conf (ComputationGraphConfiguration.java GraphBuilder)
            parent = NeuralNetConfiguration.Builder()
        self._parent = parent
        self._conf = ComputationGraphConfiguration(
            seed=parent._seed,
            updater=parent._updater,
            gradient_normalization=parent._grad_norm,
            gradient_normalization_threshold=parent._grad_norm_threshold,
        )
        self._defaults = parent._defaults

    def add_inputs(self, *names: str):
        self._conf.network_inputs.extend(names)
        return self

    def set_input_types(self, *its: InputType):
        for name, it in zip(self._conf.network_inputs, its):
            self._conf.input_types[name] = it
        return self

    def add_layer(self, name: str, layer: LayerConf, *inputs: str,
                  preprocessor: Optional[Preprocessor] = None):
        from deeplearning4j_tpu.nn.conf.network import apply_global_defaults
        apply_global_defaults(layer, self._defaults)
        layer.name = name
        self._conf.vertices[name] = LayerVertex(layer=layer, preprocessor=preprocessor)
        self._conf.vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs: str):
        self._conf.vertices[name] = vertex
        self._conf.vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str):
        self._conf.network_outputs = list(names)
        return self

    def build(self):
        conf = self._conf
        if not conf.network_inputs:
            raise ValueError("graph has no inputs")
        if not conf.network_outputs:
            raise ValueError("graph has no outputs")
        for name in conf.vertices:
            for i in conf.vertex_inputs.get(name, []):
                if i not in conf.vertices and i not in conf.network_inputs:
                    raise ValueError(f"vertex '{name}' input '{i}' is undefined")
        conf.topological_order()  # validates acyclicity
        return conf
