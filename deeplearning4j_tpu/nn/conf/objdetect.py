"""YOLOv2 object detection output layer + NMS utilities.

TPU-native equivalent of nn/conf/layers/objdetect/Yolo2OutputLayer (config)
+ nn/layers/objdetect/Yolo2OutputLayer.java (714 LoC: YOLOv2 loss,
DetectedObject extraction, NMS). The reference hand-writes the loss gradient;
here the loss is a pure function over the [N, B*(5+C), H, W] activation grid
and jax.grad differentiates it.

Label format (matching the reference): [N, 4+C, H, W] where channels 0-3 are
the object bounding box (x1,y1,x2,y2) in GRID units for the cell responsible,
and 4..4+C is the one-hot class, zero elsewhere; an object mask is derived
from the class channels.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConf, register_layer


@register_layer
@dataclass
class Yolo2OutputLayer(LayerConf):
    """YOLOv2 loss head (ref: conf/layers/objdetect/Yolo2OutputLayer.java
    Builder: lambdaCoord=5, lambdaNoObj=0.5, boundingBoxPriors)."""

    anchors: Sequence[Sequence[float]] = ((1.0, 1.0),)  # [B, 2] grid units
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5
    num_classes: Optional[int] = None

    def output_type(self, it):
        return it

    def _split(self, x, n_boxes, n_cls):
        """x: [N, B*(5+C), H, W] -> xy, wh, conf, cls predictions."""
        n, _, h, w = x.shape
        x = x.reshape(n, n_boxes, 5 + n_cls, h, w)
        txy = x[:, :, 0:2]
        twh = x[:, :, 2:4]
        tconf = x[:, :, 4]
        tcls = x[:, :, 5:]
        return txy, twh, tconf, tcls

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return x, state

    def activate_predictions(self, x):
        """Raw activations -> (xy in cell, wh in grid units, confidence,
        class probs) (ref: YoloUtils.activate)."""
        anchors = jnp.asarray(self.anchors)
        b = anchors.shape[0]
        n, c, h, w = x.shape
        n_cls = c // b - 5
        txy, twh, tconf, tcls = self._split(x, b, n_cls)
        xy = jax.nn.sigmoid(txy)
        wh = jnp.exp(jnp.clip(twh, -10, 6)) * anchors[None, :, :, None, None]
        conf = jax.nn.sigmoid(tconf)
        cls = jax.nn.softmax(tcls, axis=2)
        return xy, wh, conf, cls

    def compute_score(self, labels, preout, mask=None):
        """YOLOv2 loss (ref: Yolo2OutputLayer.computeLoss): squared-error on
        xy/sqrt(wh) for responsible boxes (λcoord), confidence loss with IOU
        targets, λnoobj elsewhere, squared-error class loss."""
        anchors = jnp.asarray(self.anchors, preout.dtype)
        b = anchors.shape[0]
        n, c, h, w = preout.shape
        n_cls = c // b - 5
        xy, wh, conf, cls = self.activate_predictions(preout)

        lab_box = labels[:, 0:4]  # x1,y1,x2,y2 grid units
        lab_cls = labels[:, 4:]
        obj_mask = (jnp.sum(lab_cls, axis=1) > 0).astype(preout.dtype)  # [N,H,W]

        # ground-truth center/size in grid units
        gt_cx = 0.5 * (lab_box[:, 0] + lab_box[:, 2])
        gt_cy = 0.5 * (lab_box[:, 1] + lab_box[:, 3])
        gt_w = jnp.clip(lab_box[:, 2] - lab_box[:, 0], 1e-6, None)
        gt_h = jnp.clip(lab_box[:, 3] - lab_box[:, 1], 1e-6, None)
        # offset within responsible cell
        gt_tx = gt_cx - jnp.floor(gt_cx)
        gt_ty = gt_cy - jnp.floor(gt_cy)

        # IOU of each anchor box prediction vs ground truth (shape [N,B,H,W])
        pw, ph_ = wh[:, :, 0], wh[:, :, 1]
        inter_w = jnp.minimum(pw, gt_w[:, None])
        inter_h = jnp.minimum(ph_, gt_h[:, None])
        inter = inter_w * inter_h
        union = pw * ph_ + (gt_w * gt_h)[:, None] - inter
        iou = inter / jnp.clip(union, 1e-6, None)

        # responsible anchor = argmax IOU per cell (stop-grad, like the ref's
        # discrete assignment)
        best = jax.lax.stop_gradient(jnp.argmax(iou, axis=1))  # [N,H,W]
        resp = jax.nn.one_hot(best, b, dtype=preout.dtype,
                              axis=1) * obj_mask[:, None]  # [N,B,H,W]

        # coordinate loss
        dxy = (xy[:, :, 0] - gt_tx[:, None]) ** 2 + (xy[:, :, 1] - gt_ty[:, None]) ** 2
        dwh = (jnp.sqrt(jnp.clip(wh[:, :, 0], 1e-6, None)) -
               jnp.sqrt(gt_w)[:, None]) ** 2 + \
              (jnp.sqrt(jnp.clip(wh[:, :, 1], 1e-6, None)) -
               jnp.sqrt(gt_h)[:, None]) ** 2
        coord_loss = self.lambda_coord * jnp.sum(resp * (dxy + dwh))

        # confidence loss: target IOU for responsible, 0 for the rest
        conf_target = jax.lax.stop_gradient(iou)
        conf_loss = jnp.sum(resp * (conf - conf_target) ** 2) + \
            self.lambda_no_obj * jnp.sum((1.0 - resp) * conf ** 2)

        # class loss over responsible cells
        cls_err = jnp.sum((cls - lab_cls[:, None]) ** 2, axis=2)  # [N,B,H,W]
        cls_loss = jnp.sum(resp * cls_err)

        return (coord_loss + conf_loss + cls_loss) / n

    # convenience: output layers elsewhere expose preout
    def preout(self, params, x, *, train=False, rng=None):
        return x


@dataclass
class DetectedObject:
    """One detection (ref: nn/layers/objdetect/DetectedObject.java)."""

    example: int
    center_x: float
    center_y: float
    width: float
    height: float
    predicted_class: int
    confidence: float

    def top_left(self):
        return (self.center_x - self.width / 2, self.center_y - self.height / 2)

    def bottom_right(self):
        return (self.center_x + self.width / 2, self.center_y + self.height / 2)


def get_predicted_objects(layer: Yolo2OutputLayer, preout,
                          threshold: float = 0.5) -> List[DetectedObject]:
    """Extract detections above a confidence threshold
    (ref: Yolo2OutputLayer.getPredictedObjects)."""
    xy, wh, conf, cls = layer.activate_predictions(jnp.asarray(preout))
    xy, wh, conf, cls = (np.asarray(a) for a in (xy, wh, conf, cls))
    n, b, _, h, w = xy.shape
    out: List[DetectedObject] = []
    cell_x = np.arange(w)[None, None, None, :]
    cell_y = np.arange(h)[None, None, :, None]
    score = conf * cls.max(axis=2)
    for i, bi, yi, xi in zip(*np.where(score > threshold)):
        out.append(DetectedObject(
            example=int(i),
            center_x=float(xy[i, bi, 0, yi, xi] + xi),
            center_y=float(xy[i, bi, 1, yi, xi] + yi),
            width=float(wh[i, bi, 0, yi, xi]),
            height=float(wh[i, bi, 1, yi, xi]),
            predicted_class=int(cls[i, bi, :, yi, xi].argmax()),
            confidence=float(conf[i, bi, yi, xi]),
        ))
    return out


def non_max_suppression(objs: List[DetectedObject],
                        iou_threshold: float = 0.45) -> List[DetectedObject]:
    """Greedy NMS (ref: YoloUtils.nms)."""
    objs = sorted(objs, key=lambda o: -o.confidence)
    keep: List[DetectedObject] = []
    for o in objs:
        ok = True
        for k in keep:
            if k.example != o.example or k.predicted_class != o.predicted_class:
                continue
            if _iou(o, k) > iou_threshold:
                ok = False
                break
        if ok:
            keep.append(o)
    return keep


def _iou(a: DetectedObject, b: DetectedObject) -> float:
    ax1, ay1 = a.top_left()
    ax2, ay2 = a.bottom_right()
    bx1, by1 = b.top_left()
    bx2, by2 = b.bottom_right()
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    union = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
    return inter / union if union > 0 else 0.0
