"""Layer configuration classes.

TPU-native equivalent of deeplearning4j-nn/.../nn/conf/layers/* — one typed,
JSON-round-trippable dataclass per layer type. Unlike the reference (which
splits declarative conf classes from imperative impl classes in nn/layers/*),
each conf here owns its functional ``init``/``apply``: apply is a pure
function of (params, inputs, state, rng), so `jax.grad` provides every
backward pass the reference hand-writes, and `jax.jit` compiles the whole
network into one XLA program.

Shape inference mirrors InputTypeUtil.java; parameter initialization mirrors
nn/params/* (DefaultParamInitializer, ConvolutionParamInitializer,
LSTMParamInitializer...). Param names follow the reference ("W", "b", "RW",
"gamma", "beta"...) so DL4J checkpoint import maps 1:1.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn import losses as _losses
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import convolution as _conv
from deeplearning4j_tpu.nn.layers import normalization as _norm
from deeplearning4j_tpu.nn.layers import recurrent as _rnn
from deeplearning4j_tpu.nn.weights import init_weights

import numpy as np

#: per-layer state keys carried only by the streaming rnn_time_step path
#: (stripped on ordinary forwards; cleared by rnn_clear_previous_state):
#: LSTM h/c, attention KV cache, positional-embedding offset, and the
#: direct-paged-decode view (pool pair + page table) the serving engine
#: installs around its decode dispatches (serving/paged_kernel.py)
#: (kv_page_scale_k/v: the int8 pool's [P, Hkv] amax-scale sidecars —
#: serving/quant.py; kv_page_prime: the engine's prime-through-the-
#: pool marker — its presence routes a prefill chunk through the
#: paged path on the folded-gather read, see _stream_attend_paged)
STREAM_STATE_KEYS = frozenset(
    {"h", "c", "kv_k", "kv_v", "kv_pos", "kv_abs", "kv_mask",
     "pos_offset", "kv_page_k", "kv_page_v", "kv_page_table",
     "kv_page_scale_k", "kv_page_scale_v", "kv_page_prime"})

#: streaming-state keys whose LEADING axis is the batch dimension (beam
#: search gathers these when pruning beams; kv_pos/kv_abs/pos_offset are
#: batch-independent scalars/vectors)
BATCHED_STREAM_KEYS = frozenset({"h", "c", "kv_k", "kv_v", "kv_mask"})


def reorder_stream_state(net, indices) -> None:
    """Gather the batch dimension of every carried streaming-state array
    (beam-search pruning: surviving beam b continues from parent
    indices[b]'s caches/RNN state). `indices`: int array [new_batch].
    kv_pos is normally a batch-independent scalar, but a per-row rewind
    (rewind_stream_state with an array) promotes it to [N] — gathered
    here like the caches so reordering keeps each row's own position
    (same for a rolling cache's kv_abs once promoted to [N, L])."""
    idx = jnp.asarray(indices)
    for name, s in net.state.items():
        if not isinstance(s, dict):
            continue
        net.state[name] = {
            kk: (vv[idx] if kk in BATCHED_STREAM_KEYS
                 or (kk == "kv_pos" and getattr(vv, "ndim", 0) >= 1)
                 or (kk == "kv_abs" and getattr(vv, "ndim", 0) >= 2)
                 else vv)
            for kk, vv in s.items()}
    rows = getattr(net, "_stream_pos_rows", None)
    if rows is not None:         # host row-position mirror follows
        net._stream_pos_rows = np.asarray(rows)[np.asarray(indices)]


def rewind_stream_state(net, n) -> None:
    """Rewind the last `n` streamed positions (speculative-decoding
    rollback, util/decoding.speculative_sample): position counters
    (attention kv_pos, positional-embedding pos_offset) move back by n —
    the rejected cache slots become invisible to the position-validity
    masks and are overwritten by the next write, so a rewound stream is
    exactly the stream that never saw those tokens (test-pinned).

    `n` may be an int (all rows rewind together) or an int array [N]
    (PER-ROW rewind — batched speculative decoding, where each row
    accepts a different prefix). A per-row rewind promotes kv_pos from a
    shared scalar to a [N] vector; the attention streaming path then
    writes each row's next chunk at its own slots (SelfAttentionLayer.
    _stream_attend vector-pos branch). Per-row rewind is attention-only:
    PositionalEmbeddingLayer's pos_offset stays scalar, so nets with
    learned positional tables reject array rewinds.

    Only position-indexed state can rewind: recurrent h/c carries the
    rejected steps irreversibly, so nets with streaming LSTM state
    raise. Rolling (windowed) caches additionally need
    cache_length >= window + n — a rejected write may have evicted the
    slot n positions short of the window edge."""
    per_row = np.ndim(n) > 0
    if not per_row and n == 0:
        return
    if per_row:
        n = np.asarray(n, np.int32)
        if not n.any():
            return
    check_rewindable(net, int(np.max(n)) if per_row else n)
    # ONE device dispatch for every counter (speculative decoding calls
    # this per round — per-counter updates would pay dispatch latency
    # once per layer per round)
    refs, vals = [], []
    for name, s in net.state.items():
        if not isinstance(s, dict):
            continue
        for k in ("kv_pos", "pos_offset"):
            if k in s:
                if per_row and k == "pos_offset":
                    raise ValueError(
                        "per-row rewind is attention-only: learned "
                        "positional tables carry a shared pos_offset "
                        "(use a rope or position-free model)")
                refs.append((name, k))
                vals.append(s[k])
    if refs:
        # the rewind amount is data-dependent per call (accepted-token
        # counts differ every speculative step): a tiny scalar/[S] int
        # upload is inherent to the rejection walk, not a missed cache
        # tpulint: disable=device-transfer-in-hot-loop
        new_vals = _rewind_counters(vals, jnp.asarray(n, jnp.int32))
        for (name, k), v in zip(refs, new_vals):
            s = dict(net.state[name])
            s[k] = v
            net.state[name] = s
    if per_row:
        # exact host-side row positions: the budget counters must track
        # max-over-rows (a min-subtraction would drift them upward and
        # trip check_stream_budget spuriously once rows diverge; a
        # max-subtraction would under-count and overrun the cache)
        rows = getattr(net, "_stream_pos_rows", None)
        if rows is None or len(rows) != len(n):
            base = getattr(net, "_stream_pos", None)
            if base is None:
                pm0 = getattr(net, "_stream_pos_map", None) or {}
                base = max(pm0.values(), default=0)
            rows = np.full(len(n), base, np.int64)
        new_rows = np.maximum(rows - n, 0)
        net._stream_pos_rows = new_rows
        n_scalar = int(rows.max()) - int(new_rows.max())
    else:
        n_scalar = n
        rows = getattr(net, "_stream_pos_rows", None)
        if rows is not None:
            net._stream_pos_rows = np.maximum(rows - n, 0)
    if getattr(net, "_stream_pos", None) is not None:
        net._stream_pos = max(0, net._stream_pos - n_scalar)
    pm = getattr(net, "_stream_pos_map", None)
    if pm:
        net._stream_pos_map = {k: max(0, v - n_scalar)
                               for k, v in pm.items()}


@jax.jit
def _rewind_counters(vals, n):
    return [jnp.maximum(v - n, 0) for v in vals]


def check_rewindable(net, n: int) -> None:
    """Validate that `net` can rewind up to `n` streamed positions
    (rewind_stream_state preconditions) — speculative_sample calls this
    ONCE at entry with n = gamma, so a non-rewindable net fails fast
    instead of mid-generation at the first data-dependent rejection."""
    if n < 0:
        raise ValueError(f"rewind must be >= 0, got {n}")
    for s in net.state.values():
        if isinstance(s, dict) and ("h" in s or "c" in s):
            raise ValueError(
                "rewind_stream_state: recurrent h/c streaming state "
                "cannot be rewound (LSTM layers do not support "
                "speculative rollback)")
    layers = list(getattr(net, "layers", None) or []) or [
        getattr(v, "layer", None)
        for v in (getattr(net.conf, "vertices", None) or {}).values()]
    for l in layers:
        # static check too: a freshly-cleared stream has no h/c in state
        # yet, but the layer WILL carry it as soon as it streams
        if getattr(l, "carries_recurrent_state", False):
            raise ValueError(
                "rewind_stream_state: recurrent h/c streaming state "
                "cannot be rewound (LSTM layers do not support "
                "speculative rollback)")
        w = getattr(l, "window", None)
        if w and getattr(l, "supports_streaming", False):
            L = getattr(l, "cache_length", 0)
            if L < w + n:
                raise ValueError(
                    f"rewinding {n} positions on a rolling cache needs "
                    f"cache_length >= window + n ({L} < {w + n}) — the "
                    "rejected writes evicted still-in-window slots")


#: (mesh, axis) sharding the streaming KV caches over their slot axis, or
#: None (single-device caches). Module-level like use_cnn_data_format —
#: set through MultiLayerNetwork/ComputationGraph.set_stream_cache_sharding,
#: which also invalidates the nets' jit caches.
_STREAM_CACHE_SHARDING: Optional[Tuple[Any, str]] = None


def set_stream_cache_sharding(mesh, axis: str = "data") -> None:
    """Shard streaming attention KV caches over the sequence (slot) axis
    of `mesh` (None disables).

    With this set, the carried kv_k/kv_v ([N,Hkv,L,D]) and kv_mask
    ([N,L]) get a sharding constraint partitioning L across the mesh —
    per-device cache memory is O(L/n). XLA partitions the incremental
    cache writes and the cache attention accordingly, inserting the
    cross-device combine for the softmax — the jit-native form of
    sequence-parallel streaming decode (sample_stream / rnn_time_step
    work unchanged; SURVEY §5 long-context)."""
    global _STREAM_CACHE_SHARDING
    _STREAM_CACHE_SHARDING = None if mesh is None else (mesh, axis)


#: the direct paged-decode implementation the streaming attention layer
#: dispatches when a page table rides the state: ("xla", False) folds the
#: pool[table] gather into the attention op (any backend); ("pallas", i)
#: runs the serving/paged_kernel.py paged-attention kernel (i = interpret
#: mode, for CPU exactness tests). Module-level like
#: _STREAM_CACHE_SHARDING — part of every streaming jit key, so flipping
#: it retraces instead of silently reusing the other impl's trace.
_PAGED_DECODE_IMPL: Tuple[str, bool] = ("xla", False)


def set_paged_decode_impl(impl: str, interpret: bool = False) -> None:
    """Select the direct paged-decode attention implementation
    (process-wide, like set_stream_cache_sharding): ``"xla"`` — the
    any-backend fallback where the attention reads K/V through the page
    table with the gather folded into the dispatch; ``"pallas"`` — the
    TPU paged-attention kernel (``interpret=True`` emulates it on CPU
    for exactness tests). The serving engine sets this from
    ``PagedKVConfig.decode_impl`` at construction."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"paged decode impl must be 'xla' or 'pallas', "
                         f"got {impl!r}")
    global _PAGED_DECODE_IMPL
    _PAGED_DECODE_IMPL = (impl, bool(interpret))


def paged_decode_impl() -> Tuple[str, bool]:
    """The LIVE (impl, interpret) pair direct paged dispatches run
    under right now. Process-wide: a later engine's construction can
    flip it, retracing every direct engine's next dispatch onto the
    new impl — consumers that model per-impl behavior (the engine's
    KV-traffic accounting, health()) must read this, not a
    construction-time snapshot."""
    return _PAGED_DECODE_IMPL


def _shard_cache(x, n_lead: int):
    """Sharding-constrain a streaming-cache array whose slot axis sits at
    position n_lead (kc/vc: 2, kv_mask: 1). No-op when unconfigured."""
    if _STREAM_CACHE_SHARDING is None or x is None:
        return x
    mesh, axis = _STREAM_CACHE_SHARDING
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(*([None] * n_lead), axis, *([None] * (x.ndim - n_lead - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def stream_capacity(layers):
    """Smallest streaming-position capacity over `layers` (None if
    unbounded): max_length always caps; cache_length caps only for
    non-windowed layers (a rolling window cache never fills up)."""
    limit = None
    for l in layers:
        if not getattr(l, "supports_streaming", False):
            continue
        windowed = getattr(l, "window", None) is not None
        caps = [getattr(l, "max_length", 0)]
        if not windowed:
            caps.append(getattr(l, "cache_length", 0))
        for cap in caps:
            if cap:
                limit = cap if limit is None else min(limit, cap)
    return limit


def check_stream_budget(net, t: int, layers, pad: int = 0) -> int:
    """Host-side guard for streaming inference: dynamic_update_slice
    CLAMPS out-of-range starts, so streaming past a layer's KV-cache /
    positional capacity would silently corrupt instead of erroring.
    Tracks net._stream_pos (reset by rnn_clear_previous_state).

    `pad` left-pad positions (packed padded priming) are free: they
    never enter a cache nor advance a position.

    Validates only — returns the would-be position; the caller commits
    it to net._stream_pos AFTER the forward succeeds, so neither a
    rejected oversized call nor a forward-raised error (e.g. a
    mid-stream mask) inflates the counter past the real cache state."""
    new_pos = getattr(net, "_stream_pos", 0) + int(t) - int(pad)
    limit = stream_capacity(layers)
    if limit is not None and new_pos > limit:
        raise ValueError(
            f"streamed {new_pos} positions, exceeding the smallest "
            f"streaming capacity ({limit}); call rnn_clear_previous_state() "
            "or raise cache_length/max_length")
    return new_pos

# ---------------------------------------------------------------------------
# registry + serde
# ---------------------------------------------------------------------------

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_to_dict(layer) -> dict:
    d = {"@class": type(layer).__name__}
    for f in dataclasses.fields(layer):
        v = getattr(layer, f.name)
        if f.name == "constraints" and v:  # list OR tuple of constraints
            v = [c.to_dict() for c in v]
        elif hasattr(v, "to_dict") and f.name in ("dropout", "weight_noise"):
            v = v.to_dict()
        elif isinstance(v, tuple):
            v = list(v)
        d[f.name] = v
    return d


def layer_from_dict(d: dict):
    d = dict(d)
    cls_name = d.pop("@class")
    cls = LAYER_REGISTRY.get(cls_name)
    if cls is None:
        raise ValueError(f"Unknown layer class '{cls_name}'")
    names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {k: v for k, v in d.items() if k in names}
    if isinstance(kwargs.get("dropout"), dict):
        from deeplearning4j_tpu.nn.conf.dropout import dropout_from_dict
        kwargs["dropout"] = dropout_from_dict(kwargs["dropout"])
    if isinstance(kwargs.get("weight_noise"), dict):
        from deeplearning4j_tpu.nn.conf.dropout import weight_noise_from_dict
        kwargs["weight_noise"] = weight_noise_from_dict(kwargs["weight_noise"])
    if kwargs.get("constraints"):
        from deeplearning4j_tpu.nn.conf.constraints import constraint_from_dict
        kwargs["constraints"] = [
            constraint_from_dict(c) if isinstance(c, dict) else c
            for c in kwargs["constraints"]]
    return cls(**kwargs)


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


# ---------------------------------------------------------------------------
# base classes
# ---------------------------------------------------------------------------


@dataclass
class LayerConf:
    """Base for all layer configs (ref: nn/conf/layers/Layer.java)."""

    name: Optional[str] = None
    # DL4J semantics: `dropout` is the RETAIN probability applied to the layer
    # INPUT during training (ref: conf/dropout/Dropout.java); 0.0 = disabled.
    # Also accepts an IDropout object (AlphaDropout, GaussianDropout, ...).
    dropout: Any = 0.0
    # optional IWeightNoise (DropConnect/WeightNoise) applied to this
    # layer's params during training (ref: conf/weightnoise/)
    weight_noise: Any = None
    # weight constraints projected after each update (ref: conf/constraint/)
    constraints: Any = None

    # -- protocol ----------------------------------------------------------
    def output_type(self, it: InputType) -> InputType:
        return it

    def init(self, key, it: InputType) -> Tuple[dict, dict]:
        """Return (params, state) pytrees for this layer."""
        return {}, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        """Return (y, new_state). Must be pure/traceable."""
        raise NotImplementedError

    def output_mask(self, mask, it: InputType):
        """Propagate a [batch, time] mask through this layer (ref: feedForwardMaskArray)."""
        return mask

    # regularization coefficients collected by the network loss
    def l1_coeffs(self) -> Dict[str, float]:
        return {}

    def l2_coeffs(self) -> Dict[str, float]:
        return {}

    def maybe_dropout_input(self, x, train, rng):
        if not train or rng is None:
            return x
        if hasattr(self.dropout, "apply_dropout"):  # IDropout object
            return self.dropout.apply_dropout(x, rng)
        if isinstance(self.dropout, (int, float)) and 0.0 < self.dropout < 1.0:
            keep = self.dropout
            m = jax.random.bernoulli(rng, keep, x.shape)
            return jnp.where(m, x / keep, 0.0)
        return x

    def to_dict(self):
        return layer_to_dict(self)


@dataclass
class BaseLayerConf(LayerConf):
    """Base for parameterized layers (ref: conf/layers/BaseLayer.java):
    activation / weight init / bias init / L1-L2 regularization."""

    activation: str = "identity"
    weight_init: str = "xavier"
    dist: Optional[dict] = None
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    learning_rate: Optional[float] = None  # per-layer LR override
    updater: Optional[dict] = None  # per-layer updater override

    def l1_coeffs(self):
        d = {}
        if self.l1:
            d["W"] = self.l1
            d["RW"] = self.l1
        if self.l1_bias:
            d["b"] = self.l1_bias
        return d

    def l2_coeffs(self):
        d = {}
        if self.l2:
            d["W"] = self.l2
            d["RW"] = self.l2
        if self.l2_bias:
            d["b"] = self.l2_bias
        return d


@dataclass
class FeedForwardLayerConf(BaseLayerConf):
    """Base for layers with nIn/nOut (ref: conf/layers/FeedForwardLayer.java)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def infer_n_in(self, it: InputType):
        if self.n_in is None:
            self.n_in = it.flat_size()


# ---------------------------------------------------------------------------
# feed-forward layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class DenseLayer(FeedForwardLayerConf):
    """Fully-connected layer (ref: conf/layers/DenseLayer.java;
    impl nn/layers/feedforward/dense/DenseLayer.java via BaseLayer W·x+b)."""

    has_bias: bool = True

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        self.infer_n_in(it)
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class EmbeddingLayer(FeedForwardLayerConf):
    """Embedding lookup (ref: conf/layers/EmbeddingLayer.java; impl
    feedforward/embedding/EmbeddingLayer.java — input is a column of indices)."""

    has_bias: bool = True

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.flat_size()
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2:
            idx = idx[:, 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class ActivationLayer(LayerConf):
    """Standalone activation (ref: conf/layers/ActivationLayer.java)."""

    activation: str = "relu"

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return _act.get(self.activation)(x), state


@register_layer
@dataclass
class DropoutLayer(LayerConf):
    """Dropout as its own layer (ref: conf/layers/DropoutLayer.java).
    `dropout` field = retain probability (DL4J semantics)."""

    def __post_init__(self):
        if self.dropout == 0.0:
            self.dropout = 0.5

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return self.maybe_dropout_input(x, train, rng), state


# ---------------------------------------------------------------------------
# convolutional layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class ConvolutionLayer(FeedForwardLayerConf):
    """2-D convolution, NCHW (ref: conf/layers/ConvolutionLayer.java; native
    path CudnnConvolutionHelper.java:54 → here `lax.conv_general_dilated`)."""

    kernel: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = "truncate"  # truncate | strict | same
    has_bias: bool = True
    data_format: str = "NCHW"  # internal activation layout; NHWC = TPU-fast

    def output_type(self, it):
        if it.kind != "cnn":
            raise ValueError(f"ConvolutionLayer needs CNN input, got {it}")
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        oh = _conv.conv_out_size(it.height, kh, sh, ph, dh, self.convolution_mode)
        ow = _conv.conv_out_size(it.width, kw, sw, pw, dw, self.convolution_mode)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.channels
        kh, kw = _pair(self.kernel)
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        w = init_weights(key, (self.n_out, self.n_in, kh, kw), fan_in, fan_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = _conv.conv2d(x, params["W"], params.get("b"), _pair(self.stride),
                         _pair(self.padding), _pair(self.dilation),
                         self.convolution_mode, self.data_format)
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class Convolution1DLayer(FeedForwardLayerConf):
    """1-D convolution over [N, C, W] (ref: conf/layers/Convolution1DLayer.java)."""

    kernel: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def output_type(self, it):
        ow = _conv.conv_out_size(it.timesteps, self.kernel, self.stride,
                                 self.padding, self.dilation, self.convolution_mode) \
            if it.timesteps is not None else None
        return InputType.recurrent(self.n_out, ow)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        fan_in = self.n_in * self.kernel
        fan_out = self.n_out * self.kernel
        w = init_weights(key, (self.n_out, self.n_in, self.kernel), fan_in, fan_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = _conv.conv1d(x, params["W"], params.get("b"), self.stride, self.padding,
                         self.dilation, self.convolution_mode)
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed convolution (ref: later-DL4J Deconvolution2D; included for
    completeness of the conv family)."""

    def output_type(self, it):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == "same":
            oh, ow = it.height * sh, it.width * sw
        else:
            oh = sh * (it.height - 1) + kh - 2 * ph
            ow = sw * (it.width - 1) + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.channels
        kh, kw = _pair(self.kernel)
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        # conv_transpose with transpose_kernel expects [O, I, kH, kW] flipped use
        w = init_weights(key, (self.n_out, self.n_in, kh, kw), fan_in, fan_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = _conv.deconv2d(x, params["W"], params.get("b"), _pair(self.stride),
                           _pair(self.padding), self.convolution_mode,
                           self.data_format)
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class SubsamplingLayer(LayerConf):
    """2-D pooling (ref: conf/layers/SubsamplingLayer.java; native path
    CudnnSubsamplingHelper.java → here `lax.reduce_window`)."""

    pooling_type: str = "max"  # max | avg | pnorm | sum
    kernel: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: float = 2.0
    data_format: str = "NCHW"

    def output_type(self, it):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        oh = _conv.conv_out_size(it.height, kh, sh, ph, 1, self.convolution_mode)
        ow = _conv.conv_out_size(it.width, kw, sw, pw, 1, self.convolution_mode)
        return InputType.convolutional(oh, ow, it.channels)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        k, s, p = _pair(self.kernel), _pair(self.stride), _pair(self.padding)
        df = self.data_format
        pt = self.pooling_type.lower()
        if pt == "max":
            y = _conv.max_pool2d(x, k, s, p, self.convolution_mode,
                                 data_format=df)
        elif pt == "avg":
            y = _conv.avg_pool2d(x, k, s, p, self.convolution_mode,
                                 data_format=df)
        elif pt == "pnorm":
            y = _conv.pnorm_pool2d(x, k, s, p, self.pnorm,
                                   self.convolution_mode, data_format=df)
        elif pt == "sum":
            y = _conv.avg_pool2d(x, k, s, p, self.convolution_mode,
                                 data_format=df) * (k[0] * k[1])
        else:
            raise ValueError(f"unknown pooling type {self.pooling_type}")
        return y, state


@register_layer
@dataclass
class Subsampling1DLayer(LayerConf):
    """1-D pooling over [N, C, W] (ref: conf/layers/Subsampling1DLayer.java)."""

    pooling_type: str = "max"
    kernel: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"

    def output_type(self, it):
        ow = _conv.conv_out_size(it.timesteps, self.kernel, self.stride,
                                 self.padding, 1, self.convolution_mode) \
            if it.timesteps is not None else None
        return InputType.recurrent(it.size, ow)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x4 = x[:, :, None, :]  # [N,C,1,W]
        k, s, p = (1, self.kernel), (1, self.stride), (0, self.padding)
        if self.pooling_type.lower() == "max":
            y = _conv.max_pool2d(x4, k, s, p, self.convolution_mode)
        else:
            y = _conv.avg_pool2d(x4, k, s, p, self.convolution_mode)
        return y[:, :, 0, :], state


@register_layer
@dataclass
class Upsampling2DLayer(LayerConf):
    """Nearest-neighbour upsampling (ref: conf/layers/Upsampling2D.java)."""

    size: Sequence[int] = (2, 2)
    data_format: str = "NCHW"

    def output_type(self, it):
        sh, sw = _pair(self.size)
        return InputType.convolutional(it.height * sh, it.width * sw, it.channels)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return _conv.upsample2d(x, _pair(self.size), self.data_format), state


@register_layer
@dataclass
class Upsampling1DLayer(LayerConf):
    """Nearest-neighbour upsampling along time, [N, C, T] → [N, C, T*size]
    (ref: conf/layers/Upsampling1D.java; Keras UpSampling1D)."""

    size: int = 2

    def output_type(self, it):
        t = it.timesteps * self.size if it.timesteps is not None else None
        return InputType.recurrent(it.size, t)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=2), state


@register_layer
@dataclass
class ZeroPadding1DLayer(LayerConf):
    """Zero padding along time, [N, C, T] → [N, C, left+T+right]
    (ref: conf/layers/ZeroPadding1DLayer.java; Keras ZeroPadding1D)."""

    padding: Sequence[int] = (1, 1)  # (left, right); int means symmetric

    def _pads(self):
        p = self.padding
        if isinstance(p, int):
            return (p, p)
        p = list(p)
        if len(p) == 1:
            return (int(p[0]), int(p[0]))
        return (int(p[0]), int(p[1]))

    def output_type(self, it):
        l, r = self._pads()
        t = it.timesteps + l + r if it.timesteps is not None else None
        return InputType.recurrent(it.size, t)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        l, r = self._pads()
        return jnp.pad(x, ((0, 0), (0, 0), (l, r))), state


@register_layer
@dataclass
class ZeroPaddingLayer(LayerConf):
    """Zero padding [top, bottom, left, right] (ref: conf/layers/ZeroPaddingLayer.java)."""

    padding: Sequence[int] = (0, 0, 0, 0)
    data_format: str = "NCHW"

    def _pads(self):
        p = list(self.padding)
        if len(p) == 2:
            p = [p[0], p[0], p[1], p[1]]
        return p

    def output_type(self, it):
        t, b, l, r = self._pads()
        return InputType.convolutional(it.height + t + b, it.width + l + r, it.channels)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return _conv.zero_pad2d(x, self._pads(), self.data_format), state


@register_layer
@dataclass
class GlobalPoolingLayer(LayerConf):
    """Global pooling over time or spatial dims (ref: conf/layers/
    GlobalPoolingLayer.java; impl pooling/GlobalPoolingLayer.java). Mask-aware
    for RNN input like the reference (MaskedReductionUtil)."""

    pooling_type: str = "max"  # max | avg | sum | pnorm
    pnorm: float = 2.0
    collapse_dimensions: bool = True
    data_format: str = "NCHW"  # layout of 4-D (CNN) input

    def output_type(self, it):
        if it.kind == "rnn":
            return InputType.feed_forward(it.size)
        if it.kind == "cnn":
            return InputType.feed_forward(it.channels)
        return it

    def output_mask(self, mask, it):
        return None  # pooling over time consumes the mask

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        pt = self.pooling_type.lower()
        if x.ndim == 3:  # [N, C, T] — pool over time, honoring mask
            axes = (2,)
            if mask is not None:
                m = mask[:, None, :].astype(x.dtype)
                if pt == "max":
                    y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=2)
                elif pt == "avg":
                    y = jnp.sum(x * m, axis=2) / jnp.clip(jnp.sum(m, axis=2), 1e-8, None)
                elif pt == "sum":
                    y = jnp.sum(x * m, axis=2)
                else:
                    y = jnp.sum(jnp.abs(x * m) ** self.pnorm, axis=2) ** (1.0 / self.pnorm)
                return y, state
        elif x.ndim == 4:  # [N, C, H, W] (or [N, H, W, C] internal NHWC)
            axes = (2, 3) if self.data_format == "NCHW" else (1, 2)
        else:
            axes = tuple(range(1, x.ndim))
        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt == "avg":
            y = jnp.mean(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "pnorm":
            y = jnp.sum(jnp.abs(x) ** self.pnorm, axis=axes) ** (1.0 / self.pnorm)
        else:
            raise ValueError(f"unknown pooling type {self.pooling_type}")
        return y, state


# ---------------------------------------------------------------------------
# normalization layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class BatchNormalization(FeedForwardLayerConf):
    """Batch norm with running stats as explicit state (ref: conf/layers/
    BatchNormalization.java, native path CudnnBatchNormalizationHelper.java).
    Defaults match the reference: eps=1e-5, decay=0.9, gamma=1, beta=0."""

    eps: float = 1e-5
    decay: float = 0.9
    lock_gamma_beta: bool = False
    gamma: float = 1.0
    beta: float = 0.0
    data_format: str = "NCHW"

    def output_type(self, it):
        return it

    def _nf(self, it):
        return it.channels if it.kind == "cnn" else it.flat_size()

    def init(self, key, it):
        nf = self._nf(it)
        self.n_in = self.n_out = nf
        params = {}
        if not self.lock_gamma_beta:
            params["gamma"] = jnp.full((nf,), self.gamma, jnp.float32)
            params["beta"] = jnp.full((nf,), self.beta, jnp.float32)
        state = {"mean": jnp.zeros((nf,), jnp.float32),
                 "var": jnp.ones((nf,), jnp.float32)}
        return params, state

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        nf = state["mean"].shape[0]
        gamma = params.get("gamma", jnp.full((nf,), self.gamma, x.dtype))
        beta = params.get("beta", jnp.full((nf,), self.beta, x.dtype))
        ch_axis = 3 if (self.data_format == "NHWC" and x.ndim == 4) else 1
        y, new_mean, new_var = _norm.batch_norm(
            x, gamma.astype(x.dtype), beta.astype(x.dtype),
            state["mean"].astype(x.dtype), state["var"].astype(x.dtype),
            train, self.eps, self.decay, channel_axis=ch_axis
        )
        if train:  # running stats kept in fp32 regardless of compute dtype
            new_state = {"mean": new_mean.astype(jnp.float32),
                         "var": new_var.astype(jnp.float32)}
        else:
            new_state = state
        return _act.get(self.activation)(y), new_state


@register_layer
@dataclass
class LayerNormalization(FeedForwardLayerConf):
    """Layer normalization over the feature axis, per example (and per
    timestep for RNN-format input [N,F,T]). A post-parity layer the
    transformer stack needs (the reference predates it); gain/bias
    params follow the BatchNormalization naming.
    """

    eps: float = 1e-5

    def output_type(self, it):
        if it.kind == "cnn":
            raise ValueError(
                "LayerNormalization supports FF [N,F] and RNN [N,F,T] "
                "input (per-feature axis 1); use BatchNormalization for "
                "CNN activations")
        return it

    def init(self, key, it):
        nf = it.size if it.kind == "rnn" else it.flat_size()
        self.n_in = self.n_out = nf
        return {"gamma": jnp.ones((nf,), jnp.float32),
                "beta": jnp.zeros((nf,), jnp.float32)}, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        # feature axis is 1 for both [N,F] and [N,F,T]
        acc = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(acc)
        mean = xf.mean(axis=1, keepdims=True)
        var = jnp.maximum((xf * xf).mean(axis=1, keepdims=True)
                          - mean * mean, 0.0)
        y = ((xf - mean) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)
        shape = [1] * x.ndim
        shape[1] = -1
        y = y * params["gamma"].astype(x.dtype).reshape(shape) + \
            params["beta"].astype(x.dtype).reshape(shape)
        return _act.get(self.activation)(y), state


@register_layer
@dataclass
class PositionalEmbeddingLayer(FeedForwardLayerConf):
    """Adds a learned positional embedding to RNN-format input [N,F,T]
    (post-parity; attention is position-agnostic without it). Params:
    P [F, max_length]; a full-sequence forward longer than max_length is
    rejected at trace time.

    Streaming (rnn_time_step): carries "pos_offset" so each chunk gets
    the embeddings for its absolute positions — the attention-era
    equivalent of LSTM h/c carry (MultiLayerNetwork.rnnTimeStep). The
    dynamic slice CLAMPS past max_length, so the network-level
    check_stream_budget guard enforces the capacity host-side."""

    max_length: int = 1024

    supports_streaming = True

    def output_type(self, it):
        if it.kind != "rnn":
            raise ValueError("PositionalEmbeddingLayer needs RNN input")
        return it

    def init(self, key, it):
        self.n_in = self.n_out = it.size
        p = 0.02 * jax.random.normal(key, (it.size, self.max_length))
        return {"P": p.astype(jnp.float32)}, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None,
              stream=False, pad_left=None):
        t = x.shape[2]
        if t > self.max_length:
            raise ValueError(f"sequence length {t} exceeds max_length "
                             f"{self.max_length}")
        if pad_left is not None and not stream:
            raise ValueError("pad_left is only meaningful for streaming")
        if stream:
            off = state.get("pos_offset")
            if off is None:
                off = jnp.zeros((), jnp.int32)
            if pad_left is None:
                z = jnp.zeros((), off.dtype)
                emb = jax.lax.dynamic_slice(
                    params["P"], (z, off), (params["P"].shape[0], t))
                new_off = off + t
            else:
                # left-padded packed chunk: chunk position i holds the
                # (cumsum-1)-th REAL token, so it gathers that absolute
                # position's embedding; pads (clamped to 0) are garbage
                # rows discarded downstream and never advance the offset
                m0 = jnp.arange(t) >= pad_left
                cum = jnp.cumsum(m0.astype(off.dtype))
                idx = jnp.clip(off + cum - 1, 0, self.max_length - 1)
                emb = params["P"][:, idx]
                new_off = off + cum[-1]
            y = x + emb[None].astype(x.dtype)
            new_state = {**state, "pos_offset": new_off}
        else:
            y = x + params["P"][None, :, :t].astype(x.dtype)
            new_state = state
        return _act.get(self.activation)(y), new_state


@register_layer
@dataclass
class SelfAttentionLayer(FeedForwardLayerConf):
    """Multi-head self-attention over RNN-format input [N,F,T] (a
    post-parity layer — the 2017 reference has no attention). The
    attention core is the flash-style blockwise kernel
    (parallel/sequence.blockwise_attention), so long sequences run in
    O(T·block) memory on one chip; under a mesh the same layer math is
    what ring/Ulysses parallelize.

    Params: Wq/Wk/Wv/Wo [F,F] + bq/bk/bv/bo. `causal` masks the future
    (LM decoding); `n_heads` must divide n_out.

    Streaming (rnn_time_step): set `cache_length` and the layer carries a
    KV cache ("kv_k"/"kv_v"/"kv_pos") across calls — incremental decoding
    attends each new token against the cached keys instead of re-running
    the full context, the attention-era counterpart of the reference's
    stored-state rnnTimeStep (MultiLayerNetwork.java rnnTimeStep).

    `n_kv_heads` < n_heads selects grouped-query attention: K/V carry
    only n_kv_heads heads (each shared by n_heads/n_kv_heads query
    heads), shrinking Wk/Wv and — the point — the streaming KV cache by
    the same factor. n_kv_heads == n_heads (default None) is standard
    MHA; n_kv_heads == 1 is multi-query attention.

    `rope=True` applies rotary position embeddings to q/k (RoFormer):
    positions enter through rotation of the head channels, so scores
    depend only on RELATIVE offsets — no learned position table, clean
    extrapolation, and streaming decode rotates by absolute kv_pos
    (cached keys are rotated at insert time). Head dim must be even.
    """

    n_heads: int = 4
    causal: bool = True
    block_size: int = 512
    cache_length: int = 0
    n_kv_heads: Optional[int] = None
    rope: bool = False
    rope_base: float = 10000.0
    #: sliding-window width (causal only): each query sees its `window`
    #: most recent positions (Mistral-style local attention; the Pallas
    #: kernel skips out-of-window blocks). None = full attention.
    window: Optional[int] = None

    supports_streaming = True

    def output_type(self, it):
        if it.kind != "rnn":
            raise ValueError("SelfAttentionLayer needs RNN input [N,F,T]")
        return InputType.recurrent(self.n_out or it.size, it.timesteps)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        if self.n_out is None:
            self.n_out = self.n_in
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out {self.n_out} not divisible by "
                             f"n_heads {self.n_heads}")
        if self.n_kv_heads is not None and self.n_kv_heads < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got "
                             f"{self.n_kv_heads}")
        hkv = self.n_kv_heads or self.n_heads
        if self.n_heads % hkv:
            raise ValueError(f"n_heads {self.n_heads} not divisible by "
                             f"n_kv_heads {hkv}")
        d = self.n_out // self.n_heads
        if self.rope and d % 2:
            raise ValueError(f"rope needs an even head dim, got {d} "
                             f"(n_out {self.n_out} / n_heads "
                             f"{self.n_heads})")
        if self.window is not None:
            if not self.causal:
                raise ValueError("window attention requires causal=True")
            if self.window < 1:
                raise ValueError(f"window must be >= 1, got {self.window}")
        keys = jax.random.split(key, 4)
        p = {}
        for i, name in enumerate(("q", "k", "v", "o")):
            n_in = self.n_in if name != "o" else self.n_out
            n_out = hkv * d if name in ("k", "v") else self.n_out
            p["W" + name] = init_weights(keys[i], (n_in, n_out), n_in,
                                         n_out, self.weight_init, self.dist)
            p["b" + name] = jnp.zeros((n_out,), jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None,
              stream=False, pad_left=None):
        from deeplearning4j_tpu.parallel.sequence import blockwise_attention
        if pad_left is not None and not stream:
            raise ValueError("pad_left is only meaningful for streaming")
        x = self.maybe_dropout_input(x, train, rng)
        n, f, t = x.shape
        h = self.n_heads
        hkv = self.n_kv_heads or h
        d = self.n_out // h
        xt = jnp.transpose(x, (0, 2, 1))                    # [N,T,F]

        def proj(name, heads):
            y = xt @ params["W" + name] + params["b" + name]
            return y.reshape(n, t, heads, d).transpose(0, 2, 1, 3)

        q = proj("q", h)                                    # [N,H,T,D]
        k, v = proj("k", hkv), proj("v", hkv)               # [N,Hkv,T,D]
        if self.rope and not stream:
            pos = jnp.arange(t)
            q = self._rope(q, pos)
            k = self._rope(k, pos)
        if stream:
            # cache the Hkv-sized K/V (the GQA memory win), expand at
            # attend time inside _stream_attend
            o, state = self._stream_attend(q, k, v, state, mask,
                                           pad_left=pad_left)
        else:
            k, v = self._expand_kv(k, v)
            # variable-length batches: mask KEYS with -inf score bias
            # (zeroed K/V would still receive softmax mass)
            o = blockwise_attention(q, k, v, causal=self.causal,
                                    block_size=self.block_size,
                                    key_mask=mask, window=self.window)
        o = o.transpose(0, 2, 1, 3).reshape(n, t, self.n_out)
        o = o @ params["Wo"] + params["bo"]
        y = jnp.transpose(o, (0, 2, 1))                     # [N,F,T]
        return _act.get(self.activation)(y), state

    def _stream_attend(self, q, k, v, state, mask=None, pad_left=None):
        """Incremental decode: append k/v to the carried cache, attend q
        against it. Positions past cache_length are a caller error (the
        dynamic_update_slice would clamp) — size cache_length to the max
        generation length.

        A key mask ([N, T] per chunk, like the non-stream path's) is
        carried in the cache as kv_mask so padded positions stay masked
        on every later step. Masked streaming must start masked: the
        kv_mask buffer is created on the first chunk (a mask appearing
        mid-stream would leave earlier chunks' validity unrecorded).

        `pad_left` (traced scalar) selects PACKED accounting for a
        left-padded chunk (util/decoding's single-dispatch priming): the
        first pad_left positions never enter the cache (their writes
        route to an out-of-range dump slot and are dropped), real tokens
        take consecutive slots/positions as if the pads did not exist —
        so one bucketed jit shape serves every prompt length with
        results identical to unpadded chunked priming. Pad queries
        attend nothing and produce discarded rows. Mutually exclusive
        with `mask` (pads are non-existent, not masked-but-resident)."""
        if self.cache_length <= 0:
            raise ValueError(
                "SelfAttentionLayer streaming needs cache_length > 0")
        if not self.causal:
            raise ValueError("streaming decode requires causal=True")
        if state.get("kv_page_table") is not None:
            # direct paged decode: the serving engine installed the page
            # pool + table in place of a dense cache — read through the
            # table, append one token per row in place
            return self._stream_attend_paged(q, k, v, state, mask=mask,
                                             pad_left=pad_left)
        n, _, t, d = q.shape
        hkv = k.shape[1]                 # cache holds n_kv_heads heads
        L = self.cache_length
        kc = state.get("kv_k")
        fresh = kc is None
        if fresh:
            kc = jnp.zeros((n, hkv, L, d), q.dtype)
            vc = jnp.zeros((n, hkv, L, d), q.dtype)
            pos = jnp.zeros((), jnp.int32)
        else:
            vc, pos = state["kv_v"], state["kv_pos"]
        vec = getattr(pos, "ndim", 0) >= 1    # [N] per-row positions
        # (after a per-row rewind_stream_state — batched speculation)
        if pad_left is not None:
            if mask is not None:
                raise ValueError("pad_left and mask are mutually "
                                 "exclusive in streaming attention")
            if state.get("kv_mask") is not None:
                raise ValueError(
                    "left-padded (packed) priming cannot follow masked "
                    "streaming — packed writes would leave the carried "
                    "kv_mask unset for their slots; restart the stream "
                    "(rnn_clear_previous_state)")
            if vec:
                raise ValueError(
                    "packed (pad_left) priming cannot follow a per-row "
                    "rewind — restart the stream")
            m0 = jnp.arange(t) >= pad_left              # [T] valid flags
            cum = jnp.cumsum(m0.astype(pos.dtype))
            q_pos = pos + cum - 1                       # pads: pos-1
            n_new = cum[-1]
        else:
            m0 = None
            steps_t = jnp.arange(t, dtype=pos.dtype)
            # [N,T] when per-row, [T] when shared
            q_pos = pos[:, None] + steps_t if vec else pos + steps_t
            n_new = t
        if self.rope:
            abs_pos = q_pos if m0 is None else jnp.maximum(q_pos, 0)
            q = self._rope(q, abs_pos)
            k = self._rope(k, abs_pos)
        if self.window is not None:
            return self._stream_attend_rolling(
                q, k, v, state, kc, vc, pos, mask, fresh=fresh,
                m0=m0, q_pos=q_pos, n_new=n_new, vec=vec)
        z = jnp.zeros((), pos.dtype)
        if vec:
            # per-row scatter at each row's own slots (advanced indexing
            # puts the two index axes first: value is [N,T,Hkv,D]);
            # out-of-range rows (past cache_length) drop their writes
            bidx = jnp.arange(n)[:, None]
            kc = kc.at[bidx, :, q_pos, :].set(
                k.transpose(0, 2, 1, 3).astype(kc.dtype), mode="drop")
            vc = vc.at[bidx, :, q_pos, :].set(
                v.transpose(0, 2, 1, 3).astype(vc.dtype), mode="drop")
        elif m0 is None:
            kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                              (z, z, pos, z))
            vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                              (z, z, pos, z))
        else:
            # packed scatter: pads route to the out-of-range dump slot L
            # and are DROPPED — they never occupy cache capacity
            slots = jnp.where(m0, q_pos, L)
            kc = kc.at[:, :, slots, :].set(k.astype(kc.dtype), mode="drop")
            vc = vc.at[:, :, slots, :].set(v.astype(vc.dtype), mode="drop")
        kc, vc = _shard_cache(kc, 2), _shard_cache(vc, 2)
        if vec:
            km = self._stream_mask_update(
                state, mask, n, t, L, fresh=fresh,
                write=lambda km, m: km.at[jnp.arange(n)[:, None],
                                          q_pos].set(m, mode="drop"))
            km = _shard_cache(km, 1)
        elif m0 is None:
            km = self._stream_mask_update(
                state, mask, n, t, L, fresh=fresh,
                write=lambda km, m: jax.lax.dynamic_update_slice(
                    km, m, (z, pos)))
            km = _shard_cache(km, 1)
        else:
            km = None
        # grouped attend against the UN-expanded cache: q reshaped to
        # [N, Hkv, reps, T, D] — materializing a repeated cache would
        # forfeit GQA's decode bandwidth win
        # query at absolute position p sees cache slots <= p
        k_idx = jnp.arange(L)
        if vec:
            valid = k_idx[None, None, :] <= q_pos[..., None]  # [N, T, L]
        else:
            valid = (k_idx[None, :] <= q_pos[:, None])[None]  # [1, T, L]
        if km is not None:
            valid = valid & km[:, None, :]                    # [N, T, L]
        o = self._grouped_attend(q, kc, vc, valid)
        out = {**state, "kv_k": kc, "kv_v": vc, "kv_pos": pos + n_new}
        if km is not None:
            out["kv_mask"] = km
        return o, out

    def _stream_attend_paged(self, q, k, v, state, mask=None,
                             pad_left=None):
        """Direct paged decode: K/V live in the block-paged pool
        (``kv_page_k``/``kv_page_v`` — [P, Hkv, page_size, D]) and the
        per-row page table (``kv_page_table`` — [N, n_max], 0 = null
        page), installed by the serving engine around its decode
        dispatches. The chunk's new tokens append with ONE
        [N, T, Hkv, D] scatter at each row's ``(page, offset)`` — an
        O(one-token) write, vs the legacy full-arena scatter_pages —
        then the queries attend against the pool through the table:

        - ``"xla"`` impl (any backend): the ``pool[table]`` gather is
          folded into this dispatch and feeds the SAME
          ``_grouped_attend`` the dense arena runs — outputs are
          bit-identical to the slot arena by construction (valid
          positions hold the exact bytes the dense cache would; masked
          positions are finite garbage ``-1e30`` hides, the dense
          path's own idle-slot argument).
        - ``"pallas"`` impl: serving/paged_kernel.py — the table is a
          scalar-prefetched index map, so only live pages are read
          (O(active context), the true paged-attention read path);
          width T = 1 + gamma runs the same kernel for the widened
          speculative verify dispatch.

        Contract (the engine's decode shape): per-row ``kv_pos``
        vector, packed maskless chunks, no rolling window. Appends past
        a row's allocation or capacity route to the null page 0 —
        transient speculative overflow (rewound before it is ever
        visible) and idle-slot coasting both land where nothing reads.
        Prefix-shared read-only blocks are safe by block alignment: a
        row appends only at positions ≥ its own fresh blocks.

        Two state-structure extensions ride the same dispatch (both
        Python-level — pytree structure keys the jit cache, so each
        combination is its own trace and the plain bf16 decode graph
        is untouched):

        - ``kv_page_prime`` present: this chunk is the engine's
          PRIME-THROUGH-THE-POOL prefill (batch 1, the int8 path —
          quantize-once means the prompt's pool bytes must be written
          by the same quantized append the decode steps use, never
          densely primed and converted). ``pad_left`` is then allowed
          with the dense path's packed accounting, pads and
          prefix-shared positions route to the null page
          (``q_pos < pos``), and the read is FORCED onto the folded
          XLA gather regardless of the live impl — the kernel's
          uniform-width causality has no notion of packed pads, and a
          rebuild's re-prime must retrace the identical read math.
        - ``kv_page_scale_k``/``_v`` present: the pool is int8 with
          per-(page, head) amax-scale sidecars (serving/quant.py) —
          appends quantize under the page base's scale, reads
          dequantize in the gather (XLA) or in VMEM (the kernel, with
          scales riding the scalar prefetch)."""
        prime = state.get("kv_page_prime") is not None
        if mask is not None or (pad_left is not None and not prime):
            raise ValueError(
                "direct paged decode is packed/maskless (the engine's "
                "decode dispatch shape) — masked or left-padded chunks "
                "must prime through the dense path")
        if self.window is not None:
            raise ValueError("rolling (windowed) caches are not "
                             "pageable (no stable token->page map)")
        kp, vp = state["kv_page_k"], state["kv_page_v"]
        table = state["kv_page_table"]
        ksc = state.get("kv_page_scale_k")
        quant = ksc is not None
        pos = state.get("kv_pos")
        if pos is None or getattr(pos, "ndim", 0) < 1:
            raise ValueError(
                "direct paged decode needs the per-row kv_pos vector "
                "(the engine arena carries one; a scalar-position "
                "stream has no per-slot pages to address)")
        n, hkv, t, d = k.shape
        L = self.cache_length
        ps = kp.shape[2]
        n_blk = table.shape[1]
        if prime and pad_left is not None:
            # packed pad accounting, the dense prime's (_stream_attend):
            # pads take q_pos = pos - 1 and never advance the stream
            m0 = jnp.arange(t) >= pad_left                  # [T] valid
            cum = jnp.cumsum(m0.astype(pos.dtype))
            q_pos = pos[:, None] + (cum - 1)[None, :]       # [N, T]
            n_new = cum[-1]
            chunk0 = pad_left
        else:
            q_pos = pos[:, None] + jnp.arange(t, dtype=pos.dtype)
            n_new = t
            chunk0 = 0
        if self.rope:
            abs_pos = jnp.maximum(q_pos, 0) if prime else q_pos
            q = self._rope(q, abs_pos)
            k = self._rope(k, abs_pos)
        # -- O(one-token) append at (page, offset) ---------------------
        blk = jnp.clip(q_pos // ps, 0, n_blk - 1).astype(jnp.int32)
        page = jnp.take_along_axis(table, blk, axis=1)
        page = jnp.where(q_pos < L, page, 0)    # past capacity: null
        if prime:
            # pads (q_pos = pos - 1) and prefix-shared positions
            # (q_pos < pos = the hit length) must not write real pages:
            # route them to the null page like past-capacity appends
            page = jnp.where(q_pos >= pos[:, None], page, 0)
        off = (q_pos % ps).astype(jnp.int32)
        kt = k.transpose(0, 2, 1, 3)                    # [N, T, Hkv, D]
        vt = v.transpose(0, 2, 1, 3)
        if quant:
            from deeplearning4j_tpu.serving.quant import quantize_chunk
            vsc = state["kv_page_scale_v"]
            writable = q_pos < L
            if prime:
                writable = writable & (q_pos >= pos[:, None])
            kq, ksc = quantize_chunk(kt, ksc, page, q_pos, pos,
                                     writable, page_size=ps,
                                     chunk0=chunk0)
            vq, vsc = quantize_chunk(vt, vsc, page, q_pos, pos,
                                     writable, page_size=ps,
                                     chunk0=chunk0)
            kp = kp.at[page, :, off, :].set(kq)
            vp = vp.at[page, :, off, :].set(vq)
        else:
            kp = kp.at[page, :, off, :].set(kt.astype(kp.dtype))
            vp = vp.at[page, :, off, :].set(vt.astype(vp.dtype))
        impl, interpret = _PAGED_DECODE_IMPL
        if impl == "pallas" and not prime:
            from deeplearning4j_tpu.serving.paged_kernel import (
                paged_attention)
            reps = self.n_heads // hkv
            qg = q.reshape(n, hkv, reps * t, d)
            o = paged_attention(qg, kp, vp, table,
                                (pos + t).astype(jnp.int32),
                                query_width=t, interpret=interpret,
                                k_scales=ksc if quant else None,
                                v_scales=vsc if quant else None)
            o = o.reshape(n, self.n_heads, t, d)
        else:
            kg = kp[table]                    # [N, n_blk, Hkv, ps, D]
            vg = vp[table]
            if quant:
                # dequant folded into the gather: q * sigma is exact
                # (power-of-two sigma, serving/quant.py), so a page
                # reads back the same values on every dispatch
                kg = kg.astype(jnp.float32) * \
                    ksc[table][:, :, :, None, None]
                vg = vg.astype(jnp.float32) * \
                    vsc[table][:, :, :, None, None]
                kg = kg.astype(q.dtype)
                vg = vg.astype(q.dtype)
            kd = jnp.moveaxis(kg, 2, 1
                              ).reshape(n, hkv, n_blk * ps, d)[:, :, :L]
            vd = jnp.moveaxis(vg, 2, 1
                              ).reshape(n, hkv, n_blk * ps, d)[:, :, :L]
            valid = jnp.arange(L)[None, None, :] <= q_pos[..., None]
            o = self._grouped_attend(q, kd, vd, valid)
        out = {**state, "kv_page_k": kp, "kv_page_v": vp,
               "kv_pos": pos + n_new}
        if quant:
            out["kv_page_scale_k"] = ksc
            out["kv_page_scale_v"] = vsc
        return o, out

    def _stream_mask_update(self, state, mask, n, t, L, *, fresh, write):
        """Maintain the [N, L] cached-key validity buffer. Returns the
        updated buffer, or None when this stream has never seen a mask."""
        km = state.get("kv_mask")
        if mask is None and km is None:
            return None
        if km is None:
            if not fresh:
                raise ValueError(
                    "mask passed mid-stream to a SelfAttentionLayer that "
                    "started streaming unmasked — earlier chunks' key "
                    "validity was never recorded; restart the stream "
                    "(rnn_clear_previous_state) with the mask from the "
                    "first chunk")
            km = jnp.zeros((n, L), jnp.bool_)
        m = (jnp.ones((n, t), jnp.bool_) if mask is None
             else jnp.asarray(mask).reshape(n, t).astype(jnp.bool_))
        return write(km, m)

    def _grouped_attend(self, q, kc, vc, valid):
        """Masked attention of [N,H,T,D] queries against the un-expanded
        [N,Hkv,L,D] cache (GQA groups share KV heads); valid: [N|1, T, L]."""
        n, _, t, d = q.shape
        hkv = kc.shape[1]
        reps = self.n_heads // hkv
        qg = q.astype(jnp.float32).reshape(n, hkv, reps, t, d)
        s = jnp.einsum("ngrtd,ngld->ngrtl", qg,
                       kc.astype(jnp.float32)) / np.sqrt(d)
        s = jnp.where(valid[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("ngrtl,ngld->ngrtd", p, vc.astype(jnp.float32))
        return o.reshape(n, self.n_heads, t, d).astype(q.dtype)

    def _stream_attend_rolling(self, q, k, v, state, kc, vc, pos,
                               mask=None, *, fresh, m0=None, q_pos=None,
                               n_new=None, vec=False):
        """Windowed streaming with a ROLLING cache: slots are reused
        modulo cache_length, so generation length is unbounded with
        bounded memory (cache_length >= window keeps every in-window key
        resident; evicted keys are out of the window by construction).
        kv_abs tracks each slot's absolute position (-1 = empty).

        `m0`/`q_pos`/`n_new` arrive from _stream_attend when the chunk is
        left-padded (packed accounting — see there): pad writes route to
        the dump slot L and are dropped, so pads consume neither slots
        nor positions. The static chunk-size guards below use the padded
        length t (conservative: a padded chunk needs its full bucket to
        fit, so pick a bucket <= cache_length).

        vec=True is the per-row-positions regime (after a per-row
        rewind_stream_state — batched speculation): q_pos is [N,T], each
        row writes at its own modular slots, and kv_abs promotes from
        the shared [L] to [N,L] on the first per-row write (exact:
        before rows diverge every row's slot->abs map is identical).
        The validity test stays the same per-row recency arithmetic, so
        a rewound row's stale future entries are invisible to that row
        while other rows keep seeing their accepted keys."""
        n, _, t, d = q.shape
        hkv = k.shape[1]
        L = self.cache_length
        if L < self.window:
            raise ValueError(
                f"rolling window streaming needs cache_length >= window "
                f"({L} < {self.window})")
        if fresh:
            # empty cache: writes never evict needed keys; any t <= L ok
            if t > L:
                raise ValueError(f"priming chunk of {t} positions exceeds "
                                 f"cache_length {L}")
        elif t > L - self.window + 1:
            # mid-stream, a larger chunk would overwrite slots still
            # inside earlier queries' windows BEFORE they attend
            raise ValueError(
                f"mid-stream chunk of {t} positions would evict in-window "
                f"keys; max is cache_length - window + 1 = "
                f"{L - self.window + 1} (or raise cache_length)")
        kv_abs = state.get("kv_abs")
        if kv_abs is None:
            kv_abs = jnp.full((L,), -1, jnp.int32)
        if q_pos is None:
            steps_t = jnp.arange(t, dtype=pos.dtype)
            q_pos = pos[:, None] + steps_t if vec else pos + steps_t
            n_new = t
        if vec:
            if m0 is not None:
                raise ValueError(
                    "packed (pad_left) priming cannot follow a per-row "
                    "rewind — restart the stream")
            if kv_abs.ndim == 1:
                kv_abs = jnp.broadcast_to(kv_abs, (n, L))
            slots = q_pos % L                              # [N, T]
            bidx = jnp.arange(n)[:, None]
            kc = kc.at[bidx, :, slots, :].set(
                k.transpose(0, 2, 1, 3).astype(kc.dtype))
            vc = vc.at[bidx, :, slots, :].set(
                v.transpose(0, 2, 1, 3).astype(vc.dtype))
            kv_abs = kv_abs.at[bidx, slots].set(
                q_pos.astype(kv_abs.dtype))
            km = self._stream_mask_update(
                state, mask, n, t, L, fresh=fresh,
                write=lambda km, m: km.at[bidx, slots].set(m))
        elif m0 is None:
            slots = q_pos % L
            kc = kc.at[:, :, slots, :].set(k.astype(kc.dtype))
            vc = vc.at[:, :, slots, :].set(v.astype(vc.dtype))
            kv_abs = kv_abs.at[slots].set(q_pos.astype(kv_abs.dtype))
            km = self._stream_mask_update(
                state, mask, n, t, L, fresh=fresh,
                write=lambda km, m: km.at[:, slots].set(m))
        else:
            slots = jnp.where(m0, q_pos % L, L)      # pads -> dump, dropped
            kc = kc.at[:, :, slots, :].set(k.astype(kc.dtype), mode="drop")
            vc = vc.at[:, :, slots, :].set(v.astype(vc.dtype), mode="drop")
            kv_abs = kv_abs.at[slots].set(q_pos.astype(kv_abs.dtype),
                                          mode="drop")
            km = None
        kc, vc = _shard_cache(kc, 2), _shard_cache(vc, 2)
        km = _shard_cache(km, 1)
        reps = self.n_heads // hkv
        qg = q.astype(jnp.float32).reshape(n, hkv, reps, t, d)
        scale = 1.0 / np.sqrt(d)
        s = jnp.einsum("ngrtd,ngld->ngrtl", qg,
                       kc.astype(jnp.float32)) * scale
        if vec:
            abs_r = kv_abs[:, None, :]                       # [N, 1, L]
            valid = ((abs_r >= 0)
                     & (abs_r <= q_pos[..., None])
                     & (q_pos[..., None] - abs_r < self.window))
            # [N, T, L] — each row against its own slot->abs map
        else:
            valid = ((kv_abs[None, :] >= 0)
                     & (kv_abs[None, :] <= q_pos[:, None])
                     & (q_pos[:, None] - kv_abs[None, :] < self.window))
            valid = valid[None]                              # [1, T, L]
        if km is not None:
            valid = valid & km[:, None, :]                   # [N, T, L]
        s = jnp.where(valid[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("ngrtl,ngld->ngrtd", p, vc.astype(jnp.float32))
        o = o.reshape(n, self.n_heads, t, d).astype(q.dtype)
        out = {**state, "kv_k": kc, "kv_v": vc, "kv_abs": kv_abs,
               "kv_pos": pos + n_new}
        if km is not None:
            out["kv_mask"] = km
        return o, out

    def _rope(self, x, positions):
        """Rotary position embedding (RoFormer rotate-half convention):
        x [N,H,T,D], positions [T] absolute — or [N,T] when rows carry
        their own streaming positions (per-row rewind). Pairs channel i
        with channel i + D/2 and rotates by positions * base^(-2i/D)."""
        d = x.shape[-1]
        if d % 2:
            raise ValueError(f"rope needs an even head dim, got {d}")
        half = d // 2
        inv = self.rope_base ** (-jnp.arange(half, dtype=jnp.float32)
                                 / half)
        ang = positions.astype(jnp.float32)[..., None] * inv  # [...,T,half]
        if ang.ndim == 2:           # shared positions: [T,half]
            cos = jnp.cos(ang)[None, None].astype(x.dtype)
            sin = jnp.sin(ang)[None, None].astype(x.dtype)
        else:                       # per-row positions: [N,T,half]
            cos = jnp.cos(ang)[:, None].astype(x.dtype)
            sin = jnp.sin(ang)[:, None].astype(x.dtype)
        x1, x2 = x[..., :half], x[..., half:]
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x1 * sin + x2 * cos], axis=-1)

    def _expand_kv(self, k, v):
        """Repeat K/V heads up to n_heads for grouped-query attention
        (no-op for standard MHA)."""
        reps = self.n_heads // k.shape[1]
        if reps == 1:
            return k, v
        return (jnp.repeat(k, reps, axis=1), jnp.repeat(v, reps, axis=1))


@register_layer
@dataclass
class LocalResponseNormalization(LayerConf):
    """LRN across channels (ref: conf/layers/LocalResponseNormalization.java;
    native path CudnnLocalResponseNormalizationHelper.java). Defaults k=2,
    n=5, alpha=1e-4, beta=0.75 match the reference."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    data_format: str = "NCHW"

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        ch_axis = 3 if self.data_format == "NHWC" else 1
        return _norm.lrn(x, self.k, self.n, self.alpha, self.beta,
                         channel_axis=ch_axis), state


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class LSTM(FeedForwardLayerConf):
    """LSTM without peepholes (ref: conf/layers/LSTM.java; impl via
    LSTMHelpers.java / CudnnLSTMHelper.java → here lstm_scan). Params:
    W [nIn,4nOut], RW [nOut,4nOut], b [4nOut]; gate order (i,f,c,o);
    forget-gate bias init (ref: forgetGateBiasInit, default 1.0)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    activation: str = "tanh"

    _peephole = False
    #: streams via irreversible h/c carry — cannot rewind (speculative
    #: decoding rollback); see check_rewindable
    carries_recurrent_state = True

    def output_type(self, it):
        return InputType.recurrent(self.n_out, it.timesteps)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        h = self.n_out
        k1, k2, k3 = jax.random.split(key, 3)
        fan_in, fan_out = self.n_in, h
        w = init_weights(k1, (self.n_in, 4 * h), fan_in + h, h, self.weight_init, self.dist)
        rw = init_weights(k2, (h, 4 * h), fan_in + h, h, self.weight_init, self.dist)
        b = jnp.zeros((4 * h,), jnp.float32)
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        p = {"W": w, "RW": rw, "b": b}
        if self._peephole:
            p["P"] = jnp.zeros((3, h), jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        out, h_fin, c_fin = _rnn.lstm_scan(
            x, params["W"], params["RW"], params["b"],
            h0=state.get("h"), c0=state.get("c"),
            peephole=params.get("P"), mask=mask,
            gate_act=self.gate_activation, cell_act=self.activation,
        )
        return out, {**state, "h": h_fin, "c": c_fin}


@register_layer
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (ref: conf/layers/GravesLSTM.java;
    peephole columns per LSTMParamInitializer)."""

    _peephole = True


@register_layer
@dataclass
class GravesBidirectionalLSTM(FeedForwardLayerConf):
    """Bidirectional Graves LSTM; forward+backward outputs SUMMED
    (ref: GravesBidirectionalLSTM.java:219)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    activation: str = "tanh"

    def output_type(self, it):
        return InputType.recurrent(self.n_out, it.timesteps)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        h = self.n_out
        keys = jax.random.split(key, 4)
        p = {}
        for tag, kw, kr in (("F", keys[0], keys[1]), ("B", keys[2], keys[3])):
            w = init_weights(kw, (self.n_in, 4 * h), self.n_in + h, h,
                             self.weight_init, self.dist)
            rw = init_weights(kr, (h, 4 * h), self.n_in + h, h,
                              self.weight_init, self.dist)
            b = jnp.zeros((4 * h,), jnp.float32).at[h:2 * h].set(
                self.forget_gate_bias_init)
            p[f"W{tag}"] = w
            p[f"RW{tag}"] = rw
            p[f"b{tag}"] = b
            p[f"P{tag}"] = jnp.zeros((3, h), jnp.float32)
        return p, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = _rnn.bidirectional_sum(
            x, params["WF"], params["RWF"], params["bF"],
            params["WB"], params["RWB"], params["bB"],
            peep_f=params["PF"], peep_b=params["PB"], mask=mask,
            gate_act=self.gate_activation, cell_act=self.activation,
        )
        return y, state


@register_layer
@dataclass
class SimpleRnn(FeedForwardLayerConf):
    """Vanilla RNN h_t = act(xW + hRW + b)."""

    activation: str = "tanh"

    def output_type(self, it):
        return InputType.recurrent(self.n_out, it.timesteps)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        h = self.n_out
        k1, k2 = jax.random.split(key)
        w = init_weights(k1, (self.n_in, h), self.n_in + h, h, self.weight_init, self.dist)
        rw = init_weights(k2, (h, h), self.n_in + h, h, self.weight_init, self.dist)
        return {"W": w, "RW": rw, "b": jnp.zeros((h,), jnp.float32)}, {}

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        out, _ = _rnn.simple_rnn_scan(x, params["W"], params["RW"], params["b"],
                                      mask=mask, act=self.activation)
        return out, state


@register_layer
@dataclass
class LastTimeStepLayer(LayerConf):
    """Extract last (unmasked) timestep: [N,C,T] -> [N,C]
    (ref: graph vertex rnn/LastTimeStepVertex.java, usable as a layer)."""

    def output_type(self, it):
        return InputType.feed_forward(it.size)

    def output_mask(self, mask, it):
        return None

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        if mask is None:
            return x[:, :, -1], state
        idx = jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1  # [N]
        idx = jnp.clip(idx, 0, x.shape[2] - 1)
        y = jnp.take_along_axis(x, idx[:, None, None], axis=2)[:, :, 0]
        return y, state


# ---------------------------------------------------------------------------
# output layers
# ---------------------------------------------------------------------------


@dataclass
class BaseOutputLayerConf(FeedForwardLayerConf):
    """Base for output layers carrying a loss function
    (ref: conf/layers/BaseOutputLayer.java)."""

    loss: str = "mcxent"
    activation: str = "softmax"

    def compute_score(self, labels, preout, mask=None):
        return _losses.score(labels, preout, self.loss, self.activation, mask)


@register_layer
@dataclass
class OutputLayer(BaseOutputLayerConf):
    """Dense + loss output layer (ref: conf/layers/OutputLayer.java)."""

    has_bias: bool = True

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        self.infer_n_in(it)
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def preout(self, params, x, *, train=False, rng=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return _act.get(self.activation)(self.preout(params, x, train=train, rng=rng)), state


@register_layer
@dataclass
class RnnOutputLayer(BaseOutputLayerConf):
    """Per-timestep dense + loss over [N,C,T] (ref: conf/layers/RnnOutputLayer.java)."""

    has_bias: bool = True

    def output_type(self, it):
        return InputType.recurrent(self.n_out, it.timesteps)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.size
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, jnp.float32)
        return p, {}

    def preout(self, params, x, *, train=False, rng=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = jnp.einsum("nct,co->not", x, params["W"])
        if self.has_bias:
            y = y + params["b"][None, :, None]
        return y

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        pre = self.preout(params, x, train=train, rng=rng)
        a = _act.get(self.activation)
        if str(self.activation).lower() == "softmax":
            y = jax.nn.softmax(pre, axis=1)
        else:
            y = a(pre)
        return y, state

    def compute_score(self, labels, preout, mask=None):
        # fold time into batch: [N,C,T] -> [N*T, C]; mask [N,T] -> [N*T]
        n, c, t = preout.shape
        p2 = jnp.transpose(preout, (0, 2, 1)).reshape(n * t, c)
        l2 = jnp.transpose(labels, (0, 2, 1)).reshape(n * t, c)
        m2 = mask.reshape(n * t) if mask is not None else None
        return _losses.score(l2, p2, self.loss, self.activation, m2)


@register_layer
@dataclass
class LossLayer(BaseOutputLayerConf):
    """Parameterless loss layer (ref: conf/layers/LossLayer.java)."""

    def output_type(self, it):
        return it

    def preout(self, params, x, *, train=False, rng=None):
        return x

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return _act.get(self.activation)(x), state


@register_layer
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with center loss (ref: conf/layers/CenterLossOutputLayer.java;
    impl nn/layers/training/CenterLossOutputLayer.java). Per-class feature
    centers are non-gradient state updated by EMA (alpha), loss adds
    lambda * ||features - center_y||^2."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, key, it):
        p, s = super().init(key, it)
        s = dict(s)
        s["centers"] = jnp.zeros((self.n_out, self.n_in), jnp.float32)
        return p, s

    def center_loss(self, features, labels, state):
        centers = state["centers"]
        cls = jnp.argmax(labels, axis=-1)
        diff = features - centers[cls]
        return self.lambda_ * 0.5 * jnp.mean(jnp.sum(diff * diff, axis=-1))

    def update_centers(self, features, labels, state):
        centers = state["centers"]
        cls = jnp.argmax(labels, axis=-1)  # [N]
        onehot = jax.nn.one_hot(cls, centers.shape[0], dtype=features.dtype)  # [N,K]
        counts = jnp.sum(onehot, axis=0)[:, None]  # [K,1]
        sums = onehot.T @ features  # [K, F]
        batch_mean = sums / jnp.clip(counts, 1.0, None)
        updated = centers + self.alpha * (batch_mean - centers)
        new_centers = jnp.where(counts > 0, updated, centers)
        return {**state, "centers": new_centers}


# ---------------------------------------------------------------------------
# misc layers
# ---------------------------------------------------------------------------


@register_layer
@dataclass
class FrozenLayer(LayerConf):
    """Wrapper marking an inner layer's params as non-trainable
    (ref: nn/conf/layers/misc/FrozenLayer.java, nn/layers/FrozenLayer.java).
    The network applies stop_gradient to its params during training."""

    inner: Optional[dict] = None  # serialized inner layer conf

    def __post_init__(self):
        if isinstance(self.inner, LayerConf):
            self._inner_obj = self.inner
            self.inner = layer_to_dict(self._inner_obj)
        elif self.inner is not None:
            self._inner_obj = layer_from_dict(self.inner)
        else:
            self._inner_obj = None

    @property
    def layer(self) -> LayerConf:
        return self._inner_obj

    def output_type(self, it):
        return self._inner_obj.output_type(it)

    def output_mask(self, mask, it):
        return self._inner_obj.output_mask(mask, it)

    def init(self, key, it):
        return self._inner_obj.init(key, it)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        params = jax.lax.stop_gradient(params)
        return self._inner_obj.apply(params, x, state, train=train, rng=rng, mask=mask)


@register_layer
@dataclass
class AutoEncoder(FeedForwardLayerConf):
    """Denoising autoencoder pretrain layer (ref: conf/layers/AutoEncoder.java;
    impl feedforward/autoencoder/AutoEncoder.java). Params W, b (hidden bias),
    vb (visible bias); decode uses W^T (tied weights)."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"
    activation: str = "sigmoid"

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        self.infer_n_in(it)
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        return {"W": w, "b": jnp.zeros((self.n_out,), jnp.float32),
                "vb": jnp.zeros((self.n_in,), jnp.float32)}, {}

    def encode(self, params, x):
        return _act.get(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return _act.get(self.activation)(h @ params["W"].T + params["vb"])

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        """Denoising reconstruction loss for layerwise pretraining
        (ref: AutoEncoder.computeGradientAndScore)."""
        xc = x
        if rng is not None and self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            xc = jnp.where(keep, x, 0.0)
        recon = self.decode(params, self.encode(params, xc))
        return jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))


@register_layer
@dataclass
class RBM(FeedForwardLayerConf):
    """Restricted Boltzmann machine with CD-k pretraining (ref:
    conf/layers/RBM.java + layers/feedforward/rbm/RBM.java:68).

    Params follow PretrainParamInitializer: W [nIn,nOut], hidden bias b,
    visible bias vb. Forward activation = propUp (same as the reference's
    use as a feedforward layer once pretrained).

    Pretraining uses the standard free-energy formulation of contrastive
    divergence: loss = mean(F(v0) - F(v_k)) with the chain sample v_k under
    stop_gradient, so jax.grad yields exactly the CD-k update
    (⟨v h⟩_data − ⟨v h⟩_model) that the reference hand-codes. Gibbs chain
    runs in probability space when sample=False (deterministic; used by
    gradient checks) or with Bernoulli sampling when an rng is given.

    hidden_unit: "binary" | "rectified"; visible_unit: "binary" | "gaussian"
    (reference HiddenUnit/VisibleUnit enums, the two pairs it actually
    supports in practice)."""

    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1  # CD-k Gibbs steps
    sparsity: float = 0.0
    activation: str = "sigmoid"
    loss: str = "mse"

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        self.infer_n_in(it)
        w = init_weights(key, (self.n_in, self.n_out), self.n_in, self.n_out,
                         self.weight_init, self.dist)
        return {"W": w, "b": jnp.zeros((self.n_out,), jnp.float32),
                "vb": jnp.zeros((self.n_in,), jnp.float32)}, {}

    def prop_up(self, params, v):
        z = v @ params["W"] + params["b"]
        if self.hidden_unit == "rectified":
            return jax.nn.relu(z)
        return jax.nn.sigmoid(z)

    def prop_down(self, params, h):
        z = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return z  # mean of unit-variance Gaussian
        return jax.nn.sigmoid(z)

    def free_energy(self, params, v):
        """F(v) = -v·vb + 0.5|v-vb|² (gaussian) − Σ softplus(b + vW).

        Closed form is exact for BINARY hidden units only; rectified-hidden
        pretraining uses the energy-statistic loss in pretrain_loss instead."""
        hidden_term = jnp.sum(jax.nn.softplus(v @ params["W"] + params["b"]),
                              axis=-1)
        if self.visible_unit == "gaussian":
            visible_term = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
            return visible_term - hidden_term
        return -(v @ params["vb"]) - hidden_term

    def _energy_statistic(self, params, v):
        """E(v, h(v)) with the hidden activations under stop_gradient: its
        parameter gradient is the CD sufficient statistic (v⊗h, h, v) for
        any hidden nonlinearity (how the reference accumulates wGradient/
        hBiasGradient/vBiasGradient in RBM.java computeGradientAndScore)."""
        h = jax.lax.stop_gradient(self.prop_up(params, v))
        if self.visible_unit == "gaussian":
            visible = 0.5 * jnp.sum((v - params["vb"]) ** 2, axis=-1)
        else:
            visible = -(v @ params["vb"])
        return visible - jnp.sum((v @ params["W"]) * h, axis=-1) \
            - (h @ params["b"])

    def gibbs_step(self, params, v, rng):
        h = self.prop_up(params, v)
        if rng is not None and self.hidden_unit == "binary":
            k1, k2 = jax.random.split(rng)
            h = jax.random.bernoulli(k1, h).astype(v.dtype)
        else:
            k2 = rng
        v_new = self.prop_down(params, h)
        if k2 is not None and self.visible_unit == "gaussian":
            v_new = v_new + jax.random.normal(k2, v_new.shape, v_new.dtype)
        return v_new

    def contrastive_divergence(self, params, v0, rng, sample: bool = True):
        """Run the CD-k chain, return v_k (no gradient flows through it)."""
        v = v0
        for i in range(max(1, self.k)):
            step_rng = (jax.random.fold_in(rng, i)
                        if (rng is not None and sample) else None)
            v = self.gibbs_step(params, v, step_rng)
        return jax.lax.stop_gradient(v)

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return _act.get(self.activation)(x @ params["W"] + params["b"]), state

    def pretrain_loss(self, params, x, rng, sample: bool = True):
        vk = self.contrastive_divergence(params, x, rng, sample=sample)
        energy = (self.free_energy if self.hidden_unit == "binary"
                  else self._energy_statistic)
        loss = jnp.mean(energy(params, x) - energy(params, vk))
        if self.sparsity > 0:
            h_mean = jnp.mean(self.prop_up(params, x), axis=0)
            loss = loss + self.sparsity * jnp.sum((h_mean - 0.01) ** 2)
        return loss
