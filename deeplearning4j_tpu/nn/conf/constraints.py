"""Weight constraints applied after each parameter update.

Equivalent of deeplearning4j-nn nn/conf/constraint/ (MaxNormConstraint,
MinMaxNormConstraint, NonNegativeConstraint, UnitNormConstraint — SURVEY
§2.2 "Dropout/noise/constraints"). Constraints are projected inside the
jitted train step right after the updater applies the step, matching the
reference's applyConstraints call at the end of each iteration.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp


@dataclass
class LayerConstraint:
    """Base (ref: api/layers/LayerConstraint.java). ``dimensions`` are the
    axes the norm is taken over — DL4J's default for dense weights is the
    input dimension (axis 0)."""
    dimensions: Tuple[int, ...] = (0,)
    apply_to_weights: bool = True
    apply_to_biases: bool = False

    def applies_to(self, param_name: str) -> bool:
        if param_name.startswith("b"):
            return self.apply_to_biases
        return self.apply_to_weights

    def apply(self, w):
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@constraint": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d

    def _norm(self, w):
        dims = tuple(d for d in self.dimensions if d < w.ndim)
        if not dims:
            dims = (0,)
        return jnp.sqrt(jnp.sum(w * w, axis=dims, keepdims=True) + 1e-12)


@dataclass
class MaxNormConstraint(LayerConstraint):
    """Rescale columns whose norm exceeds max_norm
    (ref: constraint/MaxNormConstraint.java)."""
    max_norm: float = 1.0

    def apply(self, w):
        n = self._norm(w)
        scale = jnp.minimum(1.0, self.max_norm / n)
        return w * scale


@dataclass
class MinMaxNormConstraint(LayerConstraint):
    """Clamp norms into [min, max] with interpolation rate
    (ref: constraint/MinMaxNormConstraint.java)."""
    min_norm: float = 0.0
    max_norm: float = 1.0
    rate: float = 1.0

    def apply(self, w):
        n = self._norm(w)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        target = w * (clipped / n)
        return w + self.rate * (target - w)


@dataclass
class NonNegativeConstraint(LayerConstraint):
    """Project weights onto >= 0 (ref: constraint/NonNegativeConstraint.java)."""

    def apply(self, w):
        return jnp.maximum(w, 0.0)


@dataclass
class UnitNormConstraint(LayerConstraint):
    """Normalize to unit norm (ref: constraint/UnitNormConstraint.java)."""

    def apply(self, w):
        return w / self._norm(w)


_CONSTRAINT_REGISTRY = {c.__name__: c for c in
                        (MaxNormConstraint, MinMaxNormConstraint,
                         NonNegativeConstraint, UnitNormConstraint)}


def constraint_from_dict(d: dict) -> LayerConstraint:
    cls = _CONSTRAINT_REGISTRY[d["@constraint"]]
    kwargs = {k: (tuple(v) if k == "dimensions" else v)
              for k, v in d.items() if not k.startswith("@")}
    return cls(**kwargs)


def apply_constraints(layer_confs, params: dict) -> dict:
    """Apply each layer's constraints to its param subtree (pure — usable
    inside jit). ``params`` maps layer key -> {param name -> array}."""
    out = dict(params)
    for key, sub in params.items():
        try:
            lconf = layer_confs[int(key)] if isinstance(layer_confs, list) \
                else layer_confs.get(key)
        except (ValueError, KeyError, IndexError):
            lconf = None
        cons = getattr(lconf, "constraints", None)
        if not cons or not isinstance(sub, dict):
            continue
        new_sub = dict(sub)
        for c in cons:
            for pname, w in new_sub.items():
                if c.applies_to(pname) and hasattr(w, "ndim") and w.ndim >= 1:
                    new_sub[pname] = c.apply(w)
        out[key] = new_sub
    return out
