"""Variational autoencoder layer.

TPU-native equivalent of nn/conf/layers/variational/VariationalAutoencoder
(config) + nn/layers/variational/VariationalAutoencoder.java (1163 LoC impl,
own pretrain loss): encoder MLP → (mean, logvar) → reparameterized z →
decoder MLP → reconstruction distribution. The reference hand-writes the
ELBO gradient; here -ELBO is a pure function and jax.grad does the rest.

Reconstruction distributions (ref: variational/{GaussianReconstruction
Distribution, BernoulliReconstructionDistribution}.java): "gaussian" and
"bernoulli".
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (FeedForwardLayerConf,
                                               register_layer)
from deeplearning4j_tpu.nn.weights import init_weights

# math (not jnp): a module-scope device op would initialize the default
# backend at import time, before callers can select a platform.
_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


@register_layer
@dataclass
class VariationalAutoencoder(FeedForwardLayerConf):
    encoder_layer_sizes: Sequence[int] = (256,)
    decoder_layer_sizes: Sequence[int] = (256,)
    reconstruction_distribution: str = "gaussian"  # gaussian | bernoulli
    pzx_activation: str = "identity"  # activation for the mean head
    activation: str = "relu"  # hidden activation
    num_samples: int = 1

    def output_type(self, it):
        return InputType.feed_forward(self.n_out)

    def init(self, key, it):
        if self.n_in is None:
            self.n_in = it.flat_size()
        sizes_enc = [self.n_in] + list(self.encoder_layer_sizes)
        # decoder mirrors: z -> hidden -> reconstruction params
        recon_params = self.n_in * (2 if self.reconstruction_distribution ==
                                    "gaussian" else 1)
        sizes_dec = [self.n_out] + list(self.decoder_layer_sizes)
        n_keys = (len(sizes_enc) - 1) + 2 + (len(sizes_dec) - 1) + 1
        keys = jax.random.split(key, n_keys)
        ki = iter(keys)
        p = {}
        for i in range(len(sizes_enc) - 1):
            a, b = sizes_enc[i], sizes_enc[i + 1]
            p[f"eW{i}"] = init_weights(next(ki), (a, b), a, b, self.weight_init,
                                       self.dist)
            p[f"eb{i}"] = jnp.zeros((b,), jnp.float32)
        h = sizes_enc[-1]
        p["muW"] = init_weights(next(ki), (h, self.n_out), h, self.n_out,
                                self.weight_init, self.dist)
        p["mub"] = jnp.zeros((self.n_out,), jnp.float32)
        p["lvW"] = init_weights(next(ki), (h, self.n_out), h, self.n_out,
                                self.weight_init, self.dist)
        p["lvb"] = jnp.zeros((self.n_out,), jnp.float32)
        for i in range(len(sizes_dec) - 1):
            a, b = sizes_dec[i], sizes_dec[i + 1]
            p[f"dW{i}"] = init_weights(next(ki), (a, b), a, b, self.weight_init,
                                       self.dist)
            p[f"db{i}"] = jnp.zeros((b,), jnp.float32)
        hd = sizes_dec[-1]
        p["rW"] = init_weights(next(ki), (hd, recon_params), hd, recon_params,
                               self.weight_init, self.dist)
        p["rb"] = jnp.zeros((recon_params,), jnp.float32)
        return p, {}

    # ---- pieces ----
    def encode(self, params, x) -> Tuple[jax.Array, jax.Array]:
        a = _act.get(self.activation)
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = a(h @ params[f"eW{i}"] + params[f"eb{i}"])
        mu = _act.get(self.pzx_activation)(h @ params["muW"] + params["mub"])
        logvar = h @ params["lvW"] + params["lvb"]
        return mu, logvar

    def decode(self, params, z):
        a = _act.get(self.activation)
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = a(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["rW"] + params["rb"]

    def apply(self, params, x, state, *, train=False, rng=None, mask=None):
        """Forward as a feedforward layer = mean of q(z|x) (ref:
        VariationalAutoencoder.activate uses the mean values)."""
        x = self.maybe_dropout_input(x, train, rng)
        mu, _ = self.encode(params, x)
        return mu, state

    def reconstruction_log_prob(self, params, recon_raw, x):
        if self.reconstruction_distribution == "bernoulli":
            p = jax.nn.sigmoid(recon_raw)
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p), axis=-1)
        mu, logvar = jnp.split(recon_raw, 2, axis=-1)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        return jnp.sum(
            -_HALF_LOG_2PI - 0.5 * logvar - 0.5 * (x - mu) ** 2 / jnp.exp(logvar),
            axis=-1)

    def pretrain_loss(self, params, x, rng):
        """-ELBO (ref: VariationalAutoencoder.computeGradientAndScore)."""
        mu, logvar = self.encode(params, x)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mu ** 2 - 1.0 - logvar, axis=-1)
        rec = 0.0
        keys = jax.random.split(rng if rng is not None else jax.random.PRNGKey(0),
                                self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            rec = rec + self.reconstruction_log_prob(params, self.decode(params, z), x)
        rec = rec / self.num_samples
        return jnp.mean(kl - rec)

    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """Monte-carlo estimate of reconstruction log-prob for anomaly scoring
        (ref: VariationalAutoencoder.reconstructionLogProbability)."""
        mu, logvar = self.encode(params, x)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        total = 0.0
        for k in jax.random.split(rng, num_samples):
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            total = total + self.reconstruction_log_prob(params,
                                                         self.decode(params, z), x)
        return total / num_samples

    def generate(self, params, z):
        """Decode latent samples to reconstruction means
        (ref: generateAtMeanGivenZ)."""
        raw = self.decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(raw)
        mu, _ = jnp.split(raw, 2, axis=-1)
        return mu
