"""Input type shape inference.

TPU-native equivalent of nn/conf/inputs/InputType.java — carries the
per-example logical shape between layers so configs can infer nIn and
auto-insert preprocessors (ref: InputTypeUtil.java,
MultiLayerConfiguration setInputType path).

Conventions (matching the reference):
- feed-forward activations: [batch, size]
- recurrent activations:    [batch, size, timeSeriesLength]  (DL4J NCW)
- convolutional activations: [batch, channels, height, width] (NCHW)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat" | "cnn3d"
    size: Optional[int] = None  # ff/rnn feature size
    timesteps: Optional[int] = None  # rnn sequence length (None = variable)
    channels: Optional[int] = None
    height: Optional[int] = None
    width: Optional[int] = None
    depth: Optional[int] = None  # cnn3d

    # ---- factories (mirror InputType.feedForward / recurrent / convolutional) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="rnn", size=int(size),
                         timesteps=None if timesteps is None else int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", channels=int(channels), height=int(height),
                         width=int(width))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn_flat", channels=int(channels), height=int(height),
                         width=int(width), size=int(height) * int(width) * int(channels))

    @staticmethod
    def convolutional_3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn3d", channels=int(channels), depth=int(depth),
                         height=int(height), width=int(width))

    # ---- helpers ----
    def flat_size(self) -> int:
        if self.kind in ("ff", "cnn_flat"):
            return int(self.size)
        if self.kind == "rnn":
            return int(self.size)
        if self.kind == "cnn":
            return int(self.channels) * int(self.height) * int(self.width)
        if self.kind == "cnn3d":
            return int(self.channels) * int(self.depth) * int(self.height) * int(self.width)
        raise ValueError(f"no flat size for {self}")

    def example_shape(self, batch: int = 1) -> Tuple[int, ...]:
        """Array shape for a batch of this input type."""
        if self.kind in ("ff", "cnn_flat"):
            return (batch, int(self.size))
        if self.kind == "rnn":
            return (batch, int(self.size), int(self.timesteps or 1))
        if self.kind == "cnn":
            return (batch, int(self.channels), int(self.height), int(self.width))
        if self.kind == "cnn3d":
            return (batch, int(self.channels), int(self.depth), int(self.height),
                    int(self.width))
        raise ValueError(f"no example shape for {self}")

    def to_dict(self) -> dict:
        d = {"kind": self.kind}
        for f in ("size", "timesteps", "channels", "height", "width", "depth"):
            v = getattr(self, f)
            if v is not None:
                d[f] = v
        return d

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
