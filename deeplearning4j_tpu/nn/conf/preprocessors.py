"""Input preprocessors — shape adapters between layer families.

TPU-native equivalent of nn/conf/preprocessor/* (CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor, ...). Each is a
pure reshape/transpose the reference implements with explicit
preProcess/backprop pairs; here autodiff inverts them automatically.

Layout conventions (matching the reference): FF [N,F]; CNN [N,C,H,W];
RNN [N,F,T].
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

PREPROCESSOR_REGISTRY: Dict[str, type] = {}


def register_preprocessor(cls):
    PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_to_dict(p) -> dict:
    d = {"@class": type(p).__name__}
    for f in dataclasses.fields(p):
        v = getattr(p, f.name)
        if isinstance(v, tuple):
            v = list(v)
        d[f.name] = v
    return d


def preprocessor_from_dict(d: dict):
    d = dict(d)
    cls = PREPROCESSOR_REGISTRY[d.pop("@class")]
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in d.items() if k in names})


@dataclass
class Preprocessor:
    def apply(self, x, mask=None):
        raise NotImplementedError

    def output_type(self, it: InputType) -> InputType:
        raise NotImplementedError

    def output_mask(self, mask, it: InputType):
        return mask


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor(Preprocessor):
    """[N,C,H,W] -> [N, C*H*W] (ref: CnnToFeedForwardPreProcessor.java).
    Under internal NHWC the incoming tensor is [N,H,W,C]; transpose back to
    NCHW first so the flat feature order stays DL4J-compatible (checkpoint
    and Keras-import parity depend on it)."""

    height: int = 0
    width: int = 0
    channels: int = 0
    data_format: str = "NCHW"

    def apply(self, x, mask=None):
        if self.data_format == "NHWC" and x.ndim == 4:
            x = x.transpose(0, 3, 1, 2)
        return x.reshape(x.shape[0], -1)

    def output_type(self, it):
        return InputType.feed_forward(it.flat_size())


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor(Preprocessor):
    """[N, C*H*W] -> [N,C,H,W] (ref: FeedForwardToCnnPreProcessor.java);
    emits [N,H,W,C] instead under internal NHWC."""

    height: int = 0
    width: int = 0
    channels: int = 0
    data_format: str = "NCHW"

    def apply(self, x, mask=None):
        if x.ndim != 4:
            x = x.reshape(x.shape[0], self.channels, self.height, self.width)
        if self.data_format == "NHWC":
            x = x.transpose(0, 2, 3, 1)
        return x

    def output_type(self, it):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor(Preprocessor):
    """[N,F,T] -> [N*T, F] (time folded into batch;
    ref: RnnToFeedForwardPreProcessor.java)."""

    def apply(self, x, mask=None):
        n, f, t = x.shape
        return jnp.transpose(x, (0, 2, 1)).reshape(n * t, f)

    def output_type(self, it):
        return InputType.feed_forward(it.size)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor(Preprocessor):
    """[N*T, F] -> [N,F,T] (ref: FeedForwardToRnnPreProcessor.java)."""

    timesteps: int = 1

    def apply(self, x, mask=None):
        nt, f = x.shape
        n = nt // self.timesteps
        return jnp.transpose(x.reshape(n, self.timesteps, f), (0, 2, 1))

    def output_type(self, it):
        return InputType.recurrent(it.size, self.timesteps)


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor(Preprocessor):
    """[N,C,H,W] -> [N, C*H*W, T=1]... ref semantics: treat each example's
    flattened conv features as one timestep element of a sequence whose time
    dim comes from the width axis (ref: CnnToRnnPreProcessor.java maps
    [mb,C,H,W] -> [mb, C*H, W] is NOT what DL4J does — DL4J reshapes to
    [mb, C*H*W] per step of an outer time series). Here we implement the
    common DL4J usage: input [N*T,C,H,W] -> [N, C*H*W, T]."""

    height: int = 0
    width: int = 0
    channels: int = 0
    timesteps: int = 1
    data_format: str = "NCHW"

    def apply(self, x, mask=None):
        if self.data_format == "NHWC" and x.ndim == 4:
            x = x.transpose(0, 3, 1, 2)
        nt = x.shape[0]
        n = nt // self.timesteps
        flat = x.reshape(nt, -1)
        return jnp.transpose(flat.reshape(n, self.timesteps, -1), (0, 2, 1))

    def output_type(self, it):
        return InputType.recurrent(it.flat_size(), self.timesteps)


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor(Preprocessor):
    """[N,F,T] -> [N*T, C, H, W] (ref: RnnToCnnPreProcessor.java)."""

    height: int = 0
    width: int = 0
    channels: int = 0
    data_format: str = "NCHW"

    def apply(self, x, mask=None):
        n, f, t = x.shape
        flat = jnp.transpose(x, (0, 2, 1)).reshape(n * t, f)
        y = flat.reshape(n * t, self.channels, self.height, self.width)
        if self.data_format == "NHWC":
            y = y.transpose(0, 2, 3, 1)
        return y

    def output_type(self, it):
        return InputType.convolutional(self.height, self.width, self.channels)
