"""Network-level configuration: builder DSL + JSON round-trip.

TPU-native equivalent of:
- NeuralNetConfiguration.Builder (deeplearning4j-nn/.../conf/
  NeuralNetConfiguration.java:570-1138): global defaults (seed, updater,
  weight init, activation, l1/l2) cascading into per-layer configs.
- MultiLayerConfiguration (MultiLayerConfiguration.java: backprop/pretrain
  flags, tbptt lengths default 20 :62, input preprocessors, toJson/fromJson).
- ComputationGraphConfiguration.GraphBuilder (ComputationGraphConfiguration.java:
  addLayer/addVertex/addInputs/setOutputs + topology validation).

The reference's workspace/cacheMode knobs are intentionally absent: XLA buffer
assignment replaces manual memory arenas on TPU (SURVEY §3.2 note).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    LayerConf,
    BaseLayerConf,
    FeedForwardLayerConf,
    layer_from_dict,
    layer_to_dict,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
    Preprocessor,
    RnnToFeedForwardPreProcessor,
    preprocessor_from_dict,
    preprocessor_to_dict,
)
from deeplearning4j_tpu.nn.updater import Sgd, Updater, updater_from_dict, updater_to_dict

# layer kinds each layer family expects as input
_EXPECTS = {
    "ff": {"DenseLayer", "OutputLayer", "EmbeddingLayer", "AutoEncoder",
           "CenterLossOutputLayer", "BatchNormalization", "VariationalAutoencoder"},
    "cnn": {"ConvolutionLayer", "SubsamplingLayer", "Upsampling2DLayer",
            "ZeroPaddingLayer", "LocalResponseNormalization", "Deconvolution2DLayer",
            "Yolo2OutputLayer", "SpaceToDepthLayer"},
    "rnn": {"LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "SimpleRnn",
            "RnnOutputLayer", "Convolution1DLayer", "Subsampling1DLayer",
            "LastTimeStepLayer", "ZeroPadding1DLayer", "Upsampling1DLayer"},
}


def _expected_kind(layer: LayerConf) -> Optional[str]:
    name = type(layer).__name__
    if name == "FrozenLayer":
        return _expected_kind(layer.layer)
    for kind, names in _EXPECTS.items():
        if name in names:
            return kind
    return None  # agnostic (Activation, Dropout, GlobalPooling handle any)


def infer_preprocessor(it: InputType, layer: LayerConf) -> Optional[Preprocessor]:
    """Auto-insert shape adapters (ref: InputTypeUtil / MultiLayerConfiguration
    setInputType → getPreProcessorForInputType)."""
    want = _expected_kind(layer)
    if want is None:
        return None
    have = "ff" if it.kind == "cnn_flat" else it.kind
    # BatchNormalization accepts both ff and cnn input natively
    if type(layer).__name__ == "BatchNormalization" and have in ("ff", "cnn"):
        return None
    if have == want:
        return None
    if it.kind == "cnn_flat" and want == "cnn":
        return FeedForwardToCnnPreProcessor(it.height, it.width, it.channels)
    if have == "cnn" and want == "ff":
        return CnnToFeedForwardPreProcessor(it.height, it.width, it.channels)
    if have == "ff" and want == "cnn":
        raise ValueError(
            "Cannot infer FeedForwardToCnn preprocessor shape automatically; "
            "add it explicitly")
    if have == "rnn" and want == "ff":
        return RnnToFeedForwardPreProcessor()
    raise ValueError(f"No automatic preprocessor from {it} to {type(layer).__name__}")


_GLOBAL_DEFAULT_FIELDS = ("activation", "weight_init", "dist", "bias_init",
                          "l1", "l2", "l1_bias", "l2_bias", "dropout")


def apply_global_defaults(layer: LayerConf, defaults: Dict[str, Any]) -> None:
    """Cascade builder-level defaults into a layer conf, DL4J-style: a global
    value applies unless the layer explicitly set the field (detected as the
    field differing from its dataclass default)."""
    cls_defaults = {}
    for f in dataclasses.fields(layer):
        if f.default is not dataclasses.MISSING:
            cls_defaults[f.name] = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            cls_defaults[f.name] = f.default_factory()  # type: ignore
    for k, v in defaults.items():
        if v is None:
            continue
        if not hasattr(layer, k):
            continue
        if k == "activation" and not isinstance(layer, BaseLayerConf):
            continue
        if getattr(layer, k) == cls_defaults.get(k):
            setattr(layer, k, v)


def _set_cnn_data_format_fields(layers, preprocessors, fmt: str) -> None:
    """Set `data_format` on every layer/preprocessor that declares one."""
    for obj in list(layers) + list(preprocessors):
        if obj is not None and hasattr(obj, "data_format"):
            obj.data_format = fmt


@dataclass
class MultiLayerConfiguration:
    """Sequential net config (ref: MultiLayerConfiguration.java)."""

    layers: List[LayerConf] = field(default_factory=list)
    preprocessors: Dict[int, Preprocessor] = field(default_factory=dict)
    input_type: Optional[InputType] = None
    seed: int = 12345
    updater: Updater = field(default_factory=lambda: Sgd(0.1))
    backprop: bool = True
    pretrain: bool = False
    tbptt_fwd_length: int = 20  # ref default :62
    tbptt_back_length: int = 20
    tbptt: bool = False
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    dtype: str = "float32"

    # ---- shape inference ----
    def layer_input_types(self) -> List[InputType]:
        """Input type seen by each layer (after its preprocessor)."""
        if self.input_type is None:
            raise ValueError("input_type not set; call set_input_type or provide n_in")
        it = self.input_type
        out = []
        for i, layer in enumerate(self.layers):
            pre = self.preprocessors.get(i)
            if pre is not None:
                it = pre.output_type(it)
            out.append(it)
            it = layer.output_type(it)
        return out

    def output_type(self) -> InputType:
        it = self.input_type
        for i, layer in enumerate(self.layers):
            pre = self.preprocessors.get(i)
            if pre is not None:
                it = pre.output_type(it)
            it = layer.output_type(it)
        return it

    def use_cnn_data_format(self, fmt: str = "NHWC") -> "MultiLayerConfiguration":
        """Switch the INTERNAL activation layout of the CNN stack
        (performance mode; "NHWC" keeps channel work lane-aligned on TPU —
        ~10% faster ResNet-class training). The public API stays NCHW:
        inputs are [N,C,H,W], weights [O,I,kH,kW], flat feature order and
        serialized checkpoints are unchanged. Intermediate CNN activations
        (feed_forward per-layer dumps) are in `fmt` when enabled."""
        _set_cnn_data_format_fields(self.layers, self.preprocessors.values(),
                                    fmt)
        if fmt == "NHWC" and self.input_type is not None and \
                self.input_type.kind == "cnn":
            entry = self.preprocessors.get(0)
            if entry is None:
                it = self.input_type
                self.preprocessors[0] = FeedForwardToCnnPreProcessor(
                    height=it.height, width=it.width, channels=it.channels,
                    data_format=fmt)
            elif isinstance(entry, CnnToFeedForwardPreProcessor):
                # entry flatten consumes the PUBLIC NCHW input directly —
                # it must not un-transpose an NHWC tensor it never sees
                entry.data_format = "NCHW"
        return self

    # ---- serde ----
    def to_dict(self) -> dict:
        return {
            "layers": [layer_to_dict(l) for l in self.layers],
            "preprocessors": {str(k): preprocessor_to_dict(v)
                              for k, v in self.preprocessors.items()},
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "seed": self.seed,
            "updater": updater_to_dict(self.updater),
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "tbptt": self.tbptt,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
            "dtype": self.dtype,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        conf = MultiLayerConfiguration(
            layers=[layer_from_dict(l) for l in d["layers"]],
            preprocessors={int(k): preprocessor_from_dict(v)
                           for k, v in d.get("preprocessors", {}).items()},
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            seed=d.get("seed", 12345),
            updater=updater_from_dict(d["updater"]) if d.get("updater") else Sgd(0.1),
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            tbptt=d.get("tbptt", False),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
            dtype=d.get("dtype", "float32"),
        )
        return conf

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))


class ListBuilder:
    """Sequential-net builder (ref: NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, parent: "NeuralNetConfiguration.Builder"):
        self._parent = parent
        self._layers: List[LayerConf] = []
        self._preprocessors: Dict[int, Preprocessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop = True
        self._pretrain = False
        self._tbptt = False
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, *args):
        """layer(conf) or layer(index, conf)."""
        conf = args[-1]
        self._layers.append(conf)
        return self

    def input_preprocessor(self, index: int, pre: Preprocessor):
        self._preprocessors[int(index)] = pre
        return self

    def set_input_type(self, it: InputType):
        self._input_type = it
        return self

    def backprop(self, b: bool):
        self._backprop = b
        return self

    def pretrain(self, p: bool):
        self._pretrain = p
        return self

    def tbptt(self, fwd: int = 20, back: Optional[int] = None):
        self._tbptt = True
        self._tbptt_fwd = fwd
        self._tbptt_back = back if back is not None else fwd
        return self

    def build(self) -> MultiLayerConfiguration:
        g = self._parent
        for layer in self._layers:
            apply_global_defaults(layer, g._defaults)
        conf = MultiLayerConfiguration(
            layers=self._layers,
            preprocessors=dict(self._preprocessors),
            input_type=self._input_type,
            seed=g._seed,
            updater=g._updater,
            backprop=self._backprop,
            pretrain=self._pretrain,
            tbptt=self._tbptt,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            gradient_normalization=g._grad_norm,
            gradient_normalization_threshold=g._grad_norm_threshold,
        )
        if conf.input_type is not None:
            _infer_shapes_and_preprocessors(conf)
        return conf


def _infer_shapes_and_preprocessors(conf: MultiLayerConfiguration) -> None:
    """Walk the net once: auto-insert preprocessors and fill n_in fields
    (ref: MultiLayerConfiguration setInputType path)."""
    it = conf.input_type
    for i, layer in enumerate(conf.layers):
        if i not in conf.preprocessors:
            pre = infer_preprocessor(it, layer)
            if pre is not None:
                conf.preprocessors[i] = pre
        if i in conf.preprocessors:
            it = conf.preprocessors[i].output_type(it)
        tgt = layer.layer if type(layer).__name__ == "FrozenLayer" else layer
        if isinstance(tgt, FeedForwardLayerConf) and tgt.n_in is None:
            if it.kind == "cnn":
                tgt.n_in = it.channels
            else:
                tgt.n_in = it.flat_size()
        it = layer.output_type(it)


class NeuralNetConfiguration:
    """Namespace matching the reference's entry point
    (ref: NeuralNetConfiguration.Builder)."""

    class Builder:
        def __init__(self):
            self._seed = 12345
            self._updater: Updater = Sgd(0.1)
            self._defaults: Dict[str, Any] = {}
            self._grad_norm: Optional[str] = None
            self._grad_norm_threshold = 1.0

        def seed(self, s: int):
            self._seed = int(s)
            return self

        def updater(self, u: Updater):
            self._updater = u
            return self

        def learning_rate(self, lr: float):
            self._updater.learning_rate = float(lr)
            return self

        def weight_init(self, w: str):
            self._defaults["weight_init"] = w
            return self

        def dist(self, d: dict):
            self._defaults["dist"] = d
            return self

        def activation(self, a: str):
            self._defaults["activation"] = a
            return self

        def l1(self, v: float):
            self._defaults["l1"] = v
            return self

        def l2(self, v: float):
            self._defaults["l2"] = v
            return self

        def bias_init(self, v: float):
            self._defaults["bias_init"] = v
            return self

        def dropout(self, retain: float):
            self._defaults["dropout"] = retain
            return self

        def gradient_normalization(self, method: str, threshold: float = 1.0):
            self._grad_norm = method
            self._grad_norm_threshold = threshold
            return self

        def list(self) -> ListBuilder:
            return ListBuilder(self)

        def graph_builder(self):
            from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
            return GraphBuilder(self)


@dataclass
class ComputationGraphConfiguration:
    """DAG net config (ref: ComputationGraphConfiguration.java). Constructed
    via NeuralNetConfiguration.Builder().graph_builder(); see graph_conf.py."""

    vertices: Dict[str, Any] = field(default_factory=dict)  # name -> GraphVertexConf
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    input_types: Dict[str, InputType] = field(default_factory=dict)
    seed: int = 12345
    updater: Updater = field(default_factory=lambda: Sgd(0.1))
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    dtype: str = "float32"

    def topological_order(self) -> List[str]:
        """Kahn topo sort (ref: ComputationGraph.topologicalSortOrder :1190)."""
        indeg = {name: 0 for name in self.vertices}
        for name, ins in self.vertex_inputs.items():
            indeg[name] = sum(1 for i in ins if i in self.vertices)
        ready = sorted([n for n, d in indeg.items() if d == 0])
        order: List[str] = []
        children: Dict[str, List[str]] = {n: [] for n in self.vertices}
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                if i in children:
                    children[i].append(name)
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.vertices):
            raise ValueError("Graph has a cycle or disconnected vertex inputs")
        return order

    def use_cnn_data_format(self, fmt: str = "NHWC") -> "ComputationGraphConfiguration":
        """Switch the INTERNAL activation layout of the CNN stack (see
        MultiLayerConfiguration.use_cnn_data_format). Entry vertices fed by
        a CNN network input get a FeedForwardToCnn preprocessor that
        performs the one NCHW->NHWC transpose at the graph boundary."""
        from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
        layers, pres = [], []
        for v in self.vertices.values():
            if isinstance(v, LayerVertex):
                layers.append(v.layer)
                pres.append(v.preprocessor)
            elif hasattr(v, "data_format"):
                layers.append(v)
        _set_cnn_data_format_fields(layers, pres, fmt)
        if fmt != "NHWC":
            return self
        cnn_inputs = {n for n in self.network_inputs
                      if n in self.input_types and
                      self.input_types[n].kind == "cnn"}
        for name, ins in self.vertex_inputs.items():
            hit = [i for i in ins if i in cnn_inputs]
            if not hit:
                continue
            v = self.vertices[name]
            if not isinstance(v, LayerVertex):
                raise ValueError(
                    f"use_cnn_data_format: vertex {name!r} consumes CNN "
                    f"network input {hit[0]!r} directly; only layer "
                    "vertices can host the entry transpose")
            if v.preprocessor is None:
                it = self.input_types[hit[0]]
                v.preprocessor = FeedForwardToCnnPreProcessor(
                    height=it.height, width=it.width, channels=it.channels,
                    data_format=fmt)
            elif isinstance(v.preprocessor, CnnToFeedForwardPreProcessor):
                # entry flatten consumes the PUBLIC NCHW input directly
                v.preprocessor.data_format = "NCHW"
        return self

    def to_dict(self) -> dict:
        from deeplearning4j_tpu.nn.conf.graph_conf import vertex_to_dict
        return {
            "vertices": {k: vertex_to_dict(v) for k, v in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "input_types": {k: v.to_dict() for k, v in self.input_types.items()},
            "seed": self.seed,
            "updater": updater_to_dict(self.updater),
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
            "dtype": self.dtype,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.graph_conf import vertex_from_dict
        return ComputationGraphConfiguration(
            vertices={k: vertex_from_dict(v) for k, v in d["vertices"].items()},
            vertex_inputs={k: list(v) for k, v in d["vertex_inputs"].items()},
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            input_types={k: InputType.from_dict(v)
                         for k, v in d.get("input_types", {}).items()},
            seed=d.get("seed", 12345),
            updater=updater_from_dict(d["updater"]) if d.get("updater") else Sgd(0.1),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
            dtype=d.get("dtype", "float32"),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


def _graph_builder_attr():
    from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
    return GraphBuilder


# Reference spelling: ComputationGraphConfiguration.GraphBuilder()
# (ComputationGraphConfiguration.java inner class). Assigned after the class
# body to avoid a circular import with graph_conf.
class _LazyGraphBuilder:
    def __get__(self, obj, objtype=None):
        return _graph_builder_attr()


ComputationGraphConfiguration.GraphBuilder = _LazyGraphBuilder()
