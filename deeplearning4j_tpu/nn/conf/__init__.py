"""Network configuration DSL.

TPU-native equivalent of deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf:
typed, JSON-round-trippable configs built with a fluent builder
(ref: NeuralNetConfiguration.java:570-1138, MultiLayerConfiguration.java,
ComputationGraphConfiguration.java).
"""

from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.layers import *  # noqa: F401,F403
from deeplearning4j_tpu.nn.conf.variational import VariationalAutoencoder  # noqa: F401
from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer  # noqa: F401
from deeplearning4j_tpu.nn.conf.network import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
