"""Dropout variants + weight noise.

Equivalent of deeplearning4j-nn nn/conf/dropout/ (Dropout, AlphaDropout,
GaussianDropout, GaussianNoise — IDropout impls) and nn/conf/weightnoise/
(DropConnect, WeightNoise) — SURVEY §2.2 "Dropout/noise/constraints".

A layer's ``dropout`` field accepts the DL4J float shorthand (retain
probability) or one of these IDropout objects; ``weight_noise`` takes an
IWeightNoise applied to the layer's parameters during training.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# input dropout (ref: nn/conf/dropout/IDropout.java)
# ---------------------------------------------------------------------------

@dataclass
class IDropout:
    def apply_dropout(self, x, rng):
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@dropout": type(self).__name__}
        for f in dataclasses.fields(self):
            d[f.name] = getattr(self, f.name)
        return d


@dataclass
class Dropout(IDropout):
    """Inverted dropout; p = RETAIN probability (ref: Dropout.java)."""
    p: float = 0.5

    def apply_dropout(self, x, rng):
        m = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(m, x / self.p, 0.0)


@dataclass
class AlphaDropout(IDropout):
    """SELU-preserving dropout (ref: AlphaDropout.java; Klambauer et al.):
    dropped units are set to alpha', then affine-corrected so mean/variance
    of SELU activations are preserved. p = retain probability."""
    p: float = 0.5
    # fixed SELU constants (ref: AlphaDropout.java DEFAULT_ALPHA/LAMBDA)
    ALPHA = 1.6732632423543772
    LAMBDA = 1.0507009873554805

    def apply_dropout(self, x, rng):
        ap = -self.LAMBDA * self.ALPHA  # alpha'
        p = self.p
        a = (p + ap * ap * p * (1 - p)) ** -0.5
        b = -a * (1 - p) * ap
        keep = jax.random.bernoulli(rng, p, x.shape)
        return a * jnp.where(keep, x, ap) + b


@dataclass
class GaussianDropout(IDropout):
    """Multiplicative Gaussian noise N(1, sqrt(rate/(1-rate)))
    (ref: GaussianDropout.java)."""
    rate: float = 0.5

    def apply_dropout(self, x, rng):
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + std * jax.random.normal(rng, x.shape))


@dataclass
class GaussianNoise(IDropout):
    """Additive Gaussian noise (ref: GaussianNoise.java)."""
    stddev: float = 0.1

    def apply_dropout(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape)


# ---------------------------------------------------------------------------
# weight noise (ref: nn/conf/weightnoise/IWeightNoise.java)
# ---------------------------------------------------------------------------

@dataclass
class IWeightNoise:
    def apply_to_params(self, params: dict, rng) -> dict:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@weight_noise": type(self).__name__}
        for f in dataclasses.fields(self):
            d[f.name] = getattr(self, f.name)
        return d


@dataclass
class DropConnect(IWeightNoise):
    """Drop individual WEIGHTS at train time; p = retain probability
    (ref: weightnoise/DropConnect.java). Biases are left intact like the
    reference's applyToBiases=false default."""
    p: float = 0.5
    apply_to_biases: bool = False

    def apply_to_params(self, params, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if k.startswith("b") and not self.apply_to_biases:
                out[k] = v
                continue
            m = jax.random.bernoulli(jax.random.fold_in(rng, i), self.p,
                                     v.shape)
            out[k] = jnp.where(m, v / self.p, 0.0)
        return out


@dataclass
class WeightNoise(IWeightNoise):
    """Additive (or multiplicative) Gaussian noise on the weights
    (ref: weightnoise/WeightNoise.java with a normal distribution)."""
    stddev: float = 0.01
    additive: bool = True
    apply_to_biases: bool = False

    def apply_to_params(self, params, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if k.startswith("b") and not self.apply_to_biases:
                out[k] = v
                continue
            noise = self.stddev * jax.random.normal(
                jax.random.fold_in(rng, i), v.shape)
            out[k] = v + noise if self.additive else v * (1.0 + noise)
        return out


_DROPOUT_REGISTRY = {c.__name__: c for c in
                     (Dropout, AlphaDropout, GaussianDropout, GaussianNoise)}
_NOISE_REGISTRY = {c.__name__: c for c in (DropConnect, WeightNoise)}


def dropout_from_dict(d: dict) -> IDropout:
    cls = _DROPOUT_REGISTRY[d["@dropout"]]
    kwargs = {k: v for k, v in d.items() if not k.startswith("@")}
    return cls(**kwargs)


def weight_noise_from_dict(d: dict) -> IWeightNoise:
    cls = _NOISE_REGISTRY[d["@weight_noise"]]
    kwargs = {k: v for k, v in d.items() if not k.startswith("@")}
    return cls(**kwargs)
