"""Updaters (learning rules) + learning-rate schedules + gradient normalization.

TPU-native equivalent of the reference's updater stack:
- learning rules (ND4J org.nd4j.linalg.learning GradientUpdater impls, applied
  per-block by deeplearning4j-nn/.../nn/updater/UpdaterBlock.java:104-114)
- LR schedules (NeuralNetConfiguration learningRatePolicy)
- gradient normalization/clipping (ref: GradientNormalization enum applied in
  BaseMultiLayerUpdater.preApply)

Instead of the reference's flat-view-array blocks mutated in place, updater
state is an explicit pytree threaded through a pure `update` function — the
idiomatic JAX formulation (optax-style), which jit/pjit can shard alongside
params. Each updater dataclass serializes to JSON with the net config.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

UPDATER_REGISTRY: Dict[str, type] = {}


def register_updater(cls):
    UPDATER_REGISTRY[cls.__name__] = cls
    UPDATER_REGISTRY[cls.__name__.lower()] = cls
    return cls


def updater_to_dict(u) -> dict:
    d = {"@class": type(u).__name__}
    for f in dataclasses.fields(u):
        d[f.name] = getattr(u, f.name)
    return d


def updater_from_dict(d) -> "Updater":
    if isinstance(d, Updater):
        return d
    d = dict(d)
    cls = UPDATER_REGISTRY[d.pop("@class")]
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in d.items() if k in names})


# ---------------------------------------------------------------------------
# schedules (ref: LearningRatePolicy: None, Exponential, Inverse, Poly,
# Sigmoid, Step, Schedule(map))
# ---------------------------------------------------------------------------


def schedule_lr(base_lr, policy: Optional[str], iteration, *, decay_rate=0.0,
                power=1.0, steps=1.0, max_iter=10000):
    """Compute the scheduled LR at `iteration` (traceable)."""
    if not policy or policy == "none":
        return base_lr
    it = jnp.asarray(iteration, jnp.float32)
    p = policy.lower()
    if p == "exponential":
        return base_lr * decay_rate ** it
    if p == "inverse":
        return base_lr / (1.0 + decay_rate * it) ** power
    if p == "poly":
        return base_lr * (1.0 - it / max_iter) ** power
    if p == "sigmoid":
        return base_lr / (1.0 + jnp.exp(-decay_rate * (it - steps)))
    if p == "step":
        return base_lr * decay_rate ** jnp.floor(it / steps)
    raise ValueError(f"unknown LR policy {policy}")


# ---------------------------------------------------------------------------
# updaters
# ---------------------------------------------------------------------------


@dataclass
class Updater:
    """Base learning rule. init_state/update operate on a whole pytree."""

    learning_rate: float = 1e-3

    def init_state(self, params):
        return {}

    def update(self, grads, state, params, lr_scale=1.0):
        """Return (updates_to_subtract, new_state)."""
        raise NotImplementedError

    def _lr(self, lr_scale):
        return self.learning_rate * lr_scale


@register_updater
@dataclass
class Sgd(Updater):
    learning_rate: float = 0.1

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        return jax.tree_util.tree_map(lambda g: lr * g, grads), state


@register_updater
@dataclass
class Nesterovs(Updater):
    """Nesterov momentum (ref semantics: ND4J NesterovsUpdater —
    v = mu*v - lr*g; update = -(mu*v_prev - (1+mu)*v_new) equivalent form)."""

    learning_rate: float = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return {"v": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        mu = self.momentum

        def upd(g, v):
            v_new = mu * v - lr * g
            step = -(mu * v_new - lr * g)  # lookahead step
            return step, v_new

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_v = treedef.flatten_up_to(state["v"])
        pairs = [upd(g, v) for g, v in zip(flat_g, flat_v)]
        steps = treedef.unflatten([p[0] for p in pairs])
        vs = treedef.unflatten([p[1] for p in pairs])
        return steps, {"v": vs}


@register_updater
@dataclass
class Adam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"m": z, "v": jax.tree_util.tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        t = state["t"] + 1
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)
        tf = t.astype(jnp.float32)
        corr = jnp.sqrt(1.0 - b2 ** tf) / (1.0 - b1 ** tf)
        steps = jax.tree_util.tree_map(
            lambda m_, v_: lr * corr * m_ / (jnp.sqrt(v_) + self.epsilon), m, v)
        return steps, {"m": m, "v": v, "t": t}


@register_updater
@dataclass
class AdaMax(Updater):
    learning_rate: float = 2e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": jax.tree_util.tree_map(jnp.zeros_like, params),
                "u": jax.tree_util.tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        t = state["t"] + 1
        b1, b2 = self.beta1, self.beta2
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g)),
                                   state["u"], grads)
        tf = t.astype(jnp.float32)
        steps = jax.tree_util.tree_map(
            lambda m_, u_: lr / (1 - b1 ** tf) * m_ / (u_ + self.epsilon), m, u)
        return steps, {"m": m, "u": u, "t": t}


@register_updater
@dataclass
class Nadam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": jax.tree_util.tree_map(jnp.zeros_like, params),
                "v": jax.tree_util.tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        t = state["t"] + 1
        b1, b2 = self.beta1, self.beta2
        tf = t.astype(jnp.float32)
        m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state["v"], grads)

        def step(m_, v_, g):
            mhat = b1 * m_ / (1 - b1 ** (tf + 1)) + (1 - b1) * g / (1 - b1 ** tf)
            vhat = v_ / (1 - b2 ** tf)
            return lr * mhat / (jnp.sqrt(vhat) + self.epsilon)

        steps = jax.tree_util.tree_map(step, m, v, grads)
        return steps, {"m": m, "v": v, "t": t}


@register_updater
@dataclass
class RmsProp(Updater):
    learning_rate: float = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"g2": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        d = self.rms_decay
        g2 = jax.tree_util.tree_map(lambda a, g: d * a + (1 - d) * g * g,
                                    state["g2"], grads)
        steps = jax.tree_util.tree_map(
            lambda g, a: lr * g / jnp.sqrt(a + self.epsilon), grads, g2)
        return steps, {"g2": g2}


@register_updater
@dataclass
class AdaGrad(Updater):
    learning_rate: float = 1e-1
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"h": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, grads, state, params, lr_scale=1.0):
        lr = self._lr(lr_scale)
        h = jax.tree_util.tree_map(lambda a, g: a + g * g, state["h"], grads)
        steps = jax.tree_util.tree_map(
            lambda g, a: lr * g / (jnp.sqrt(a) + self.epsilon), grads, h)
        return steps, {"h": h}


@register_updater
@dataclass
class AdaDelta(Updater):
    learning_rate: float = 1.0  # unused by the rule itself (kept for API parity)
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"g2": jax.tree_util.tree_map(jnp.zeros_like, params),
                "dx2": jax.tree_util.tree_map(jnp.zeros_like, params)}

    def update(self, grads, state, params, lr_scale=1.0):
        rho, eps = self.rho, self.epsilon
        g2 = jax.tree_util.tree_map(lambda a, g: rho * a + (1 - rho) * g * g,
                                    state["g2"], grads)

        def step(g, a, d):
            s = jnp.sqrt(d + eps) / jnp.sqrt(a + eps) * g
            return s

        steps = jax.tree_util.tree_map(step, grads, g2, state["dx2"])
        dx2 = jax.tree_util.tree_map(lambda d, s: rho * d + (1 - rho) * s * s,
                                     state["dx2"], steps)
        return steps, {"g2": g2, "dx2": dx2}


@register_updater
@dataclass
class NoOp(Updater):
    def update(self, grads, state, params, lr_scale=1.0):
        return jax.tree_util.tree_map(jnp.zeros_like, grads), state


# ---------------------------------------------------------------------------
# gradient normalization (ref: GradientNormalization enum)
# ---------------------------------------------------------------------------


def normalize_gradients(grads, method: Optional[str], threshold: float = 1.0):
    """Apply the reference's GradientNormalization semantics to a grad pytree."""
    if not method or method == "none":
        return grads
    m = method.lower()
    leaves = jax.tree_util.tree_leaves(grads)
    if m == "renormalizel2pergradient" or m == "renormalize_l2_per_gradient":
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + 1e-12)
        return jax.tree_util.tree_map(lambda g: g / gnorm, grads)
    if m in ("renormalizel2perparamtype", "renormalize_l2_per_param_type"):
        return jax.tree_util.tree_map(
            lambda g: g / jnp.sqrt(jnp.sum(g * g) + 1e-12), grads)
    if m in ("clipelementwiseabsolutevalue", "clip_element_wise_absolute_value"):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    if m in ("clipl2pergradient", "clip_l2_per_gradient"):
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + 1e-12)
        scale = jnp.minimum(1.0, threshold / gnorm)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if m in ("clipl2perparamtype", "clip_l2_per_param_type"):
        def clip(g):
            n = jnp.sqrt(jnp.sum(g * g) + 1e-12)
            return g * jnp.minimum(1.0, threshold / n)
        return jax.tree_util.tree_map(clip, grads)
    raise ValueError(f"unknown gradient normalization {method}")
