"""Fused ResNet stem: space-to-depth 7×7/2 conv + BN + ReLU + 3×3/2
maxpool as Pallas kernels with a recompute backward.

Why (PERF.md round 5): with all 16 bottleneck blocks fused, the
remaining per-step HBM items outside the residual blocks are the STEM —
the BN stats/normalize and pool passes each re-traverse the 112×112×64
activation (~205 MB/pass at batch 128). The raw 7×7 conv is also
MXU-hostile: its im2col contraction is K = 7·7·3 = 147 taps of width 3.
Space-to-depth fixes both at once:

- the input reorders 224×224×3 → 112×112×12 (2×2 pixel phases become
  channels), so the 7×7/2 conv becomes a 4×4/1 conv whose im2col
  contraction is **K = 4·4·12 = 192** — one MXU-shaped matmul per
  image instead of 49 skinny taps;
- the conv kernel emits per-channel Σ/Σ² as its epilogue (batch stats
  cost zero extra traffic, the bottleneck.py pattern);
- BN-normalize + ReLU + the 3×3/2 maxpool run as ONE output-stage pass
  (read y, write the pooled 56×56×64) — the normalized activation is
  never materialized to HBM;
- the backward mirrors the bottleneck recompute pattern: pool/ReLU
  backward recomputes z from the saved raw conv output and emits the
  BN-backward sums as its epilogue; the dW pass rebuilds the im2col
  from the input; dx is the transposed 4×4 correlation in
  space-to-depth coordinates, un-shuffled back to pixels.

Per-step stem HBM traffic drops from ~6 full traversals of the 112²×64
activation (XLA plan: conv write, stats read, normalize read+write,
pool read fwd; plus the BN reductions and pool backward re-reads) to
~3 (conv write + one fused output-stage read fwd; one recompute read +
one dy round trip bwd).

Expected ceiling is ~2% of step time (PERF.md round 5) and the round-3
lesson — pallas_call boundaries can cost more than the saved traffic —
applies with full force, so this plan is NEVER engaged statically: the
graph runs it only when the kernel-crossover store
(tuning/crossover.py) holds a calibrated entry saying it wins on this
hardware. The exactness contract is the same as bottleneck.py's:
``interpret=True`` runs the identical kernels on CPU, pinned against
``reference_stem`` (the jnp composition with the unfused layer
semantics).

Maxpool tie note: the kernels send gradient to EVERY position equal to
the window max; XLA's reduce_window VJP picks one. Ties are
measure-zero for continuous activations and do not occur in the pinned
tests' random data.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from deeplearning4j_tpu.nn.layers.bottleneck import (
    _VMEM_BUDGET, BnParams, _affine, _bcast_spec, _finalize_stats,
    _img_spec)

__all__ = ["fused_stem", "fused_stem_supported", "reference_stem",
           "stem_geometry", "stem_weight_s2d"]


def stem_geometry(h: int, w: int) -> dict:
    """Static geometry of the stem at input [*, h, w, *] (NHWC): the
    7×7/2 conv pads 3 (the reference's ZeroPadding(3,3,3,3) +
    pad-0 conv), the pool is 3×3/2 pad 1. Space-to-depth needs the
    padded extent even, so the bottom/right zero pad extends to 5 (even
    h) / 4 (odd h) — the extra rows are zeros the 7-tap kernel never
    weights (taps 7 of the zero-extended 8×8 kernel are zero)."""
    pad_b = 5 if h % 2 == 0 else 4
    pad_r = 5 if w % 2 == 0 else 4
    hp, wp = h + 3 + pad_b, w + 3 + pad_r
    hs, ws = hp // 2, wp // 2
    ho, wo = (h - 1) // 2 + 1, (w - 1) // 2 + 1        # conv out
    po, pw = (ho - 1) // 2 + 1, (wo - 1) // 2 + 1      # pool out
    return {"pad_b": pad_b, "pad_r": pad_r, "hp": hp, "wp": wp,
            "hs": hs, "ws": ws, "ho": ho, "wo": wo, "po": po, "pw": pw}


def stem_weight_s2d(w4: jax.Array) -> jax.Array:
    """OIHW conv weight [K, C, 7, 7] → the space-to-depth contraction
    matrix [64·C, K]. Row index (i·4+j)·4C + (pi·2+pj)·C + c pairs tap
    (i, j) of the 4×4 s2d conv with pixel phase (pi, pj): original tap
    (a, b) = (2i+pi, 2j+pj) of the zero-extended 8×8 kernel. XLA folds
    this rearrangement into its one-time weight-prep copy."""
    k, c = w4.shape[0], w4.shape[1]
    w8 = jnp.pad(w4, ((0, 0), (0, 0), (0, 1), (0, 1)))   # [K,C,8,8]
    w8 = w8.reshape(k, c, 4, 2, 4, 2)                    # [K,C,i,pi,j,pj]
    return w8.transpose(2, 4, 3, 5, 1, 0).reshape(64 * c, k)


def _stem_vmem(h: int, w: int, c: int, k: int, bpe: int) -> int:
    """Max per-grid-step VMEM estimate over the five stem passes (one
    full image per step; fp32 where the kernels accumulate)."""
    g = stem_geometry(h, w)
    hp, wp, hs, ws = g["hp"], g["wp"], g["hs"], g["ws"]
    ho, wo, po, pw = g["ho"], g["wo"], g["po"], g["pw"]
    kdim = 64 * c
    x_b, pad_b = h * w * c * bpe, hp * wp * c * 4
    y_b, dz_b = ho * wo * k * bpe, ho * wo * k * bpe
    fwd_conv = (x_b + 2 * pad_b                    # x + padded f32 + s2d
                + ho * wo * kdim * bpe             # im2col, model dtype
                + ho * wo * k * (4 + bpe)          # fp32 acc + stored y
                + kdim * k * bpe)
    fwd_pool = (y_b + 2 * (ho + 2) * (wo + 2) * k * 4   # z + padded z
                + po * pw * k * bpe)
    bwd_pool = (y_b + po * pw * k * bpe                 # y + g
                + (ho + 2) * (wo + 2) * k * (bpe + 4)   # zc pad + dz acc
                + ho * wo * k * 4                       # z0 / relu mask
                + dz_b)
    bwd_dw = (x_b + 2 * pad_b + y_b + dz_b
              + ho * wo * k * (4 + bpe)                 # dy f32 + stored
              + kdim * k * (bpe + 4))                   # w + fp32 dW
    bwd_dx = (ho * wo * k * bpe                         # dy in
              + (hs + 3) * (ws + 3) * k * 4             # dy padded f32
              + hs * ws * 4 * c * 4                     # dx in s2d, f32
              + hp * wp * c * 4 + x_b                   # un-s2d + dx out
              + kdim * k * bpe)
    return max(fwd_conv, fwd_pool, bwd_pool, bwd_dw, bwd_dx)


def fused_stem_supported(x_shape, n_out: int, dtype) -> bool:
    """VMEM gate (the bottleneck pattern): every pass must hold one full
    image + its working set. NHWC [N, H, W, C] input; H, W ≥ 7 (the
    7-tap conv must see real pixels)."""
    if len(x_shape) != 4:
        return False
    _, h, w, c = x_shape
    if h < 7 or w < 7:
        return False
    if isinstance(dtype, str) and dtype in ("bf16", "bfloat16"):
        dtype = jnp.bfloat16
    bpe = jnp.dtype(dtype).itemsize
    return _stem_vmem(int(h), int(w), int(c), int(n_out), bpe) \
        <= _VMEM_BUDGET


# ---------------------------------------------------------------------------
# in-kernel space-to-depth helpers (shared by fwd conv and dW passes)
# ---------------------------------------------------------------------------


def _s2d_image(xf, g):
    """[h, w, c] fp32 → padded s2d grid [hs, ws, 4c] (pixel phases as
    channels, phase-major ordering matching stem_weight_s2d rows)."""
    c = xf.shape[2]
    p = jnp.pad(xf, ((3, g["pad_b"]), (3, g["pad_r"]), (0, 0)))
    return p.reshape(g["hs"], 2, g["ws"], 2, c) \
        .transpose(0, 2, 1, 3, 4).reshape(g["hs"], g["ws"], 4 * c)


def _im2col(s, g):
    """s2d grid [hs, ws, 4c] → im2col [ho·wo, 192-ish] with tap-major
    column blocks: the whole 7×7/2 conv is ONE K = 64·C contraction."""
    ho, wo = g["ho"], g["wo"]
    cols = [s[i:i + ho, j:j + wo, :].reshape(ho * wo, s.shape[2])
            for i in range(4) for j in range(4)]
    return jnp.concatenate(cols, axis=1)


# ---------------------------------------------------------------------------
# forward kernels
# ---------------------------------------------------------------------------


def _stem_conv_kernel(x_ref, w_ref, o_ref, s1_ref, s2_ref, *, g):
    """One image: y = s2d-conv(x) as one [ho·wo, 64C]·[64C, K] matmul,
    with the Σy / Σy² channel epilogue accumulated across the grid."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    _, h, w, c = x_ref.shape
    k = w_ref.shape[1]
    xf = x_ref[...].reshape(h, w, c).astype(jnp.float32)
    ic = _im2col(_s2d_image(xf, g), g).astype(w_ref.dtype)
    out = lax.dot_general(ic, w_ref[...], (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype).reshape(1, g["ho"], g["wo"], k)
    # stats of the STORED (dtype-rounded) output — the consumer
    # normalizes the rounded tensor (bottleneck.py contract)
    of = o_ref[...].reshape(g["ho"] * g["wo"], k).astype(jnp.float32)
    s1_ref[...] += jnp.sum(of, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(of * of, axis=0, keepdims=True)


def _pool_windows(zp, po, pw):
    """The nine strided 3×3/2 window views of a (+1-padded) image."""
    return [zp[i:i + 2 * po - 1:2, j:j + 2 * pw - 1:2, :]
            for i in range(3) for j in range(3)]


def _stem_pool_kernel(y_ref, aff_ref, o_ref, *, g):
    """One image of the fused output stage: normalize + ReLU + 3×3/2
    maxpool in one read of y — z never reaches HBM. aff rows [2, K]
    fp32: (sc, bb)."""
    _, ho, wo, k = y_ref.shape
    po, pw = g["po"], g["pw"]
    yf = y_ref[...].reshape(ho, wo, k).astype(jnp.float32)
    z = jnp.maximum(yf * aff_ref[0][None, None, :]
                    + aff_ref[1][None, None, :], 0.0)
    zp = jnp.pad(z, ((1, 1), (1, 1), (0, 0)),
                 constant_values=-jnp.inf)
    m = _pool_windows(zp, po, pw)
    out = functools.reduce(jnp.maximum, m)
    o_ref[...] = out.astype(o_ref.dtype).reshape(1, po, pw, k)


# ---------------------------------------------------------------------------
# backward kernels — pool/relu (+sums), then dW/dy, then dx
# ---------------------------------------------------------------------------


def _stem_bwd_pool_kernel(y_ref, g_ref, aff_ref, dz_ref, sums_ref, *, g):
    """One image: pool backward + ReLU mask, recomputing z from the raw
    conv output, with the BN-backward sums (Σdz0, Σdz0·ŷ) as the
    epilogue. aff rows [4, K] fp32: (sc, bb, inv, mu)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[...] = jnp.zeros_like(sums_ref)

    _, ho, wo, k = y_ref.shape
    po, pw = g["po"], g["pw"]
    yf = y_ref[...].reshape(ho, wo, k).astype(jnp.float32)
    z0 = yf * aff_ref[0][None, None, :] + aff_ref[1][None, None, :]
    # max-position recompute in the MODEL dtype: the stored pooled
    # output (and the reference's pooling input) are rounded values, so
    # the window-max comparisons must see the same rounding — max
    # commutes with monotone rounding, so the selected positions match
    # the forward pass (and under f32 this is exactly z)
    zc = jnp.maximum(z0, 0.0).astype(y_ref.dtype)
    zp = jnp.pad(zc, ((1, 1), (1, 1), (0, 0)),
                 constant_values=-jnp.inf)
    wins = _pool_windows(zp, po, pw)
    m = functools.reduce(jnp.maximum, wins)
    gf = g_ref[...].reshape(po, pw, k).astype(jnp.float32)
    acc = jnp.zeros((ho + 2, wo + 2, k), jnp.float32)
    for t, win in enumerate(wins):
        i_, j_ = divmod(t, 3)
        v = jnp.where(win == m, gf, 0.0)            # [po, pw, k]
        # interleave to stride-2 positions (2r, 2c), then shift by the
        # window offset — pad+reshape, no scatter (bottleneck pattern)
        v2 = jnp.pad(v.reshape(po, 1, pw, 1, k),
                     ((0, 0), (0, 1), (0, 0), (0, 1), (0, 0)))
        v2 = v2.reshape(2 * po, 2 * pw, k)[:2 * po - 1, :2 * pw - 1, :]
        acc += jnp.pad(v2, ((i_, ho + 2 - (2 * po - 1) - i_),
                            (j_, wo + 2 - (2 * pw - 1) - j_), (0, 0)))
    dz = acc[1:1 + ho, 1:1 + wo, :]
    dz0 = jnp.where(z0 > 0, dz, 0.0)
    dz_ref[...] = dz0.astype(dz_ref.dtype).reshape(1, ho, wo, k)
    # sums over the STORED (rounded) dz0: the dW/dx passes consume the
    # rounded tensor, so m1/m2 must describe the same values
    dzs = dz_ref[...].reshape(ho * wo, k).astype(jnp.float32)
    yhat = (yf.reshape(ho * wo, k) - aff_ref[3][None, :]) \
        * aff_ref[2][None, :]
    sums_ref[0:1, :] += jnp.sum(dzs, axis=0, keepdims=True)
    sums_ref[1:2, :] += jnp.sum(dzs * yhat, axis=0, keepdims=True)


def _stem_bwd_dw_kernel(x_ref, y_ref, dz_ref, aff_ref, dy_ref, dw_ref,
                        *, g):
    """One image: BN backward dy = sc·(dz0 − m1 − ŷ·m2), then the
    per-tap dW epilogue (s2d window ⊗ dy), dW accumulated across the
    grid. aff rows [6, K] fp32: (sc, bb, inv, mu, m1, m2). dy is stored
    (model dtype) for the dx pass — the one extra round trip the
    two-pass backward costs."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    _, h, w, c = x_ref.shape
    k = y_ref.shape[3]
    ho, wo = g["ho"], g["wo"]
    yf = y_ref[...].reshape(ho * wo, k).astype(jnp.float32)
    dzf = dz_ref[...].reshape(ho * wo, k).astype(jnp.float32)
    sc = aff_ref[0][None, :]
    inv = aff_ref[2][None, :]
    mu = aff_ref[3][None, :]
    m1 = aff_ref[4][None, :]
    m2 = aff_ref[5][None, :]
    dy = sc * (dzf - m1 - (yf - mu) * inv * m2)
    dy_ref[...] = dy.astype(dy_ref.dtype).reshape(1, ho, wo, k)
    xf = x_ref[...].reshape(h, w, c).astype(jnp.float32)
    s = _s2d_image(xf, g)
    c4 = s.shape[2]
    dyt = dy_ref[...].reshape(ho * wo, k)   # rounded, as the dx pass sees
    for t in range(16):
        i_, j_ = divmod(t, 4)
        win = s[i_:i_ + ho, j_:j_ + wo, :].reshape(ho * wo, c4)
        dw_ref[t * c4:(t + 1) * c4, :] += lax.dot_general(
            win.astype(y_ref.dtype), dyt,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _stem_bwd_dx_kernel(dy_ref, w_ref, dx_ref, *, g):
    """One image: dx as the transposed 4×4 correlation in s2d
    coordinates (dS[u,v] = Σ_taps dy[u−i, v−j]·W_tapᵀ), un-shuffled back
    to pixel space and cropped to the unpadded input."""
    _, ho, wo, k = dy_ref.shape
    hs, ws = g["hs"], g["ws"]
    c4 = w_ref.shape[0] // 16
    c = c4 // 4
    dyp = jnp.pad(dy_ref[...].reshape(ho, wo, k).astype(jnp.float32),
                  ((3, hs - ho), (3, ws - wo), (0, 0)))
    acc = jnp.zeros((hs * ws, c4), jnp.float32)
    for t in range(16):
        i_, j_ = divmod(t, 4)
        gs = dyp[3 - i_:3 - i_ + hs, 3 - j_:3 - j_ + ws, :] \
            .reshape(hs * ws, k)
        acc += lax.dot_general(
            gs.astype(w_ref.dtype), w_ref[t * c4:(t + 1) * c4, :],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    # reverse the s2d shuffle: [hs, ws, pi, pj, c] → [2hs, 2ws, c]
    p = acc.reshape(hs, ws, 2, 2, c).transpose(0, 2, 1, 3, 4) \
        .reshape(2 * hs, 2 * ws, c)
    h, w = dx_ref.shape[1], dx_ref.shape[2]
    dx_ref[...] = p[3:3 + h, 3:3 + w, :].astype(dx_ref.dtype) \
        .reshape(1, h, w, c)


# ---------------------------------------------------------------------------
# pallas_call dispatchers
# ---------------------------------------------------------------------------


def _conv_stats(x, w, g, interpret):
    n, h, wd, c = x.shape
    k = w.shape[1]
    ho, wo = g["ho"], g["wo"]
    out, s1, s2 = pl.pallas_call(
        functools.partial(_stem_conv_kernel, g=g),
        grid=(n,),
        in_specs=[_img_spec(h, wd, c), _bcast_spec(w.shape[0], k)],
        out_specs=[_img_spec(ho, wo, k), _bcast_spec(1, k),
                   _bcast_spec(1, k)],
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, k), x.dtype),
                   jax.ShapeDtypeStruct((1, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32)],
        interpret=interpret,
    )(x, w)
    return out, s1[0], s2[0]


def _pool(y, sc, bb, g, interpret):
    n, ho, wo, k = y.shape
    aff = jnp.stack([sc, bb]).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_stem_pool_kernel, g=g),
        grid=(n,),
        in_specs=[_img_spec(ho, wo, k), _bcast_spec(2, k)],
        out_specs=_img_spec(g["po"], g["pw"], k),
        out_shape=jax.ShapeDtypeStruct((n, g["po"], g["pw"], k),
                                       y.dtype),
        interpret=interpret,
    )(y, aff)


def _bwd_pool(y, gout, aff, g, interpret):
    n, ho, wo, k = y.shape
    dz, sums = pl.pallas_call(
        functools.partial(_stem_bwd_pool_kernel, g=g),
        grid=(n,),
        in_specs=[_img_spec(ho, wo, k), _img_spec(g["po"], g["pw"], k),
                  _bcast_spec(4, k)],
        out_specs=[_img_spec(ho, wo, k), _bcast_spec(2, k)],
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, k), y.dtype),
                   jax.ShapeDtypeStruct((2, k), jnp.float32)],
        interpret=interpret,
    )(y, gout, aff)
    return dz, sums


def _bwd_dw(x, y, dz, aff, w_shape, g, interpret):
    n, h, wd, c = x.shape
    k = y.shape[3]
    ho, wo = g["ho"], g["wo"]
    dy, dw = pl.pallas_call(
        functools.partial(_stem_bwd_dw_kernel, g=g),
        grid=(n,),
        in_specs=[_img_spec(h, wd, c), _img_spec(ho, wo, k),
                  _img_spec(ho, wo, k), _bcast_spec(6, k)],
        out_specs=[_img_spec(ho, wo, k), _bcast_spec(*w_shape)],
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, k), x.dtype),
                   jax.ShapeDtypeStruct(w_shape, jnp.float32)],
        interpret=interpret,
    )(x, y, dz, aff)
    return dy, dw


def _bwd_dx(dy, w, x_shape, g, interpret):
    n, h, wd, c = x_shape
    ho, wo = g["ho"], g["wo"]
    k = dy.shape[3]
    return pl.pallas_call(
        functools.partial(_stem_bwd_dx_kernel, g=g),
        grid=(n,),
        in_specs=[_img_spec(ho, wo, k), _bcast_spec(*w.shape)],
        out_specs=_img_spec(h, wd, c),
        out_shape=jax.ShapeDtypeStruct((n, h, wd, c), dy.dtype),
        interpret=interpret,
    )(dy, w)


# ---------------------------------------------------------------------------
# custom_vjp orchestration
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _stem_core(cfg, x, w, gamma, beta):
    """cfg = (eps, interpret). Returns (out, batch_stats2). Stat
    cotangents are ignored (running averages only — the bottleneck.py
    contract)."""
    out, res = _stem_fwd_impl(cfg, x, w, gamma, beta)
    return out, res[2]


def _stem_fwd_impl(cfg, x, w, gamma, beta):
    eps, interpret = cfg
    n, h, wd, _ = x.shape
    g = stem_geometry(h, wd)
    y, s1, s2 = _conv_stats(x, w, g, interpret)
    mu, var = _finalize_stats(s1, s2, n * g["ho"] * g["wo"])
    sc, bb, _inv = _affine(gamma, beta, mu, var, eps)
    out = _pool(y, sc, bb, g, interpret)
    return out, (x, y, (mu, var))


def _stem_vjp_fwd(cfg, x, w, gamma, beta):
    out, res = _stem_fwd_impl(cfg, x, w, gamma, beta)
    return (out, res[2]), res + ((w, gamma, beta),)


def _stem_vjp_bwd(cfg, res, cts):
    eps, interpret = cfg
    gout, _stat_cts = cts
    x, y, (mu, var), (w, gamma, beta) = res
    n, h, wd, _ = x.shape
    g = stem_geometry(h, wd)
    count = n * g["ho"] * g["wo"]
    sc, bb, inv = _affine(gamma, beta, mu, var, eps)
    k = y.shape[3]
    aff_p = jnp.stack([sc, bb, inv, mu]).astype(jnp.float32)
    dz0, sums = _bwd_pool(y, gout.astype(y.dtype), aff_p, g, interpret)
    m1, m2 = sums[0] / count, sums[1] / count
    dgamma, dbeta = sums[1], sums[0]
    aff_k = jnp.stack([sc, bb, inv, mu, m1, m2]).astype(jnp.float32)
    dy, dw = _bwd_dw(x, y, dz0, aff_k, tuple(w.shape), g, interpret)
    dx = _bwd_dx(dy, w, x.shape, g, interpret)
    return (dx, dw.astype(w.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype))


_stem_core.defvjp(_stem_vjp_fwd, _stem_vjp_bwd)


# ---------------------------------------------------------------------------
# public entry + reference oracle
# ---------------------------------------------------------------------------


def fused_stem(
    x: jax.Array,
    w: jax.Array, bn: BnParams,
    *,
    train: bool,
    eps: float = 1e-5,
    decay: float = 0.9,
    interpret: bool = False,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """The fused ResNet stem. x [N,H,W,C] NHWC raw input; w is the
    OIHW conv weight [K,C,7,7] (rearranged internally — the param
    pytree keeps the serialization layout, like the bottleneck
    plumbing). Semantics: zero-pad 3 → 7×7/2 conv (no bias) → BN →
    ReLU → 3×3/2 pad-1 maxpool.

    Returns (out [N,H//4,W//4-ish,K], new running (mean, var)) with the
    same decay rounding as layers.BatchNormalization (bottleneck.py
    ``_decayed`` contract). Inference uses running stats."""
    ws = stem_weight_s2d(w)

    def _decayed(old, new):
        return (decay * old.astype(x.dtype) + (1.0 - decay) * new) \
            .astype(jnp.float32)

    if train:
        out, (mu, var) = _stem_core((eps, interpret), x, ws,
                                    bn.gamma, bn.beta)
        return out, (_decayed(bn.running_mean, mu),
                     _decayed(bn.running_var, var))
    g = stem_geometry(x.shape[1], x.shape[2])
    sc, bb, _ = _affine(bn.gamma.astype(jnp.float32),
                        bn.beta.astype(jnp.float32),
                        bn.running_mean, bn.running_var, eps)
    y, _, _ = _conv_stats(x, ws, g, interpret)
    out = _pool(y, sc, bb, g, interpret)
    return out, (bn.running_mean, bn.running_var)


def reference_stem(x, w, bn: BnParams, *, train, eps=1e-5, decay=0.9):
    """Unfused jnp composition with IDENTICAL semantics — the
    equivalence oracle (autodiff supplies its backward): pad-3 7×7/2
    conv, one-pass BN, ReLU, 3×3/2 pad-1 maxpool — exactly the layer
    chain the ResNet50 zoo graph builds."""
    xp = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
    # f32 inputs rather than preferred_element_type: identical math
    # (bf16-valued products are exact in f32, accumulation f32 either
    # way — the reference_bottleneck precision pattern), and the conv
    # transpose rule keeps matching dtypes under AD
    y = lax.conv_general_dilated(
        xp.astype(jnp.float32),
        w.transpose(2, 3, 1, 0).astype(jnp.float32), (2, 2), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")).astype(x.dtype)
    yf = y.astype(jnp.float32)
    if train:
        mean = jnp.mean(yf, axis=(0, 1, 2))
        var = jnp.maximum(
            jnp.mean(yf * yf, axis=(0, 1, 2)) - mean * mean, 0.0)
    else:
        mean, var = bn.running_mean, bn.running_var
    inv = lax.rsqrt(var + eps)
    z = (yf - mean) * inv * bn.gamma.astype(jnp.float32) \
        + bn.beta.astype(jnp.float32)
    z = jnp.maximum(z, 0.0).astype(x.dtype)
    out = lax.reduce_window(
        z, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])
    new_mean = decay * bn.running_mean.astype(x.dtype) \
        .astype(jnp.float32) + (1 - decay) * mean
    new_var = decay * bn.running_var.astype(x.dtype) \
        .astype(jnp.float32) + (1 - decay) * var
    if not train:
        new_mean, new_var = bn.running_mean, bn.running_var
    return out, (new_mean, new_var)
