"""Recurrent ops: LSTM family as `lax.scan` with fused gate matmuls.

TPU-native equivalent of:
- LSTMHelpers (deeplearning4j-nn/.../recurrent/LSTMHelpers.java:58-785) —
  the per-timestep Java loop becomes one `lax.scan`; the input projection
  x@W for ALL timesteps is hoisted out of the scan into a single large
  matmul that XLA tiles onto the MXU (the same fusion cuDNN's fused RNN
  path performs, CudnnLSTMHelper.java:588).
- GravesLSTM peepholes (ref: GravesLSTM.java / LSTMParamInitializer peephole
  columns).
- GravesBidirectionalLSTM (ref: GravesBidirectionalLSTM.java:219 — forward and
  backward passes are SUMMED, output width = nOut).

Gate order convention here is (i, f, c, o) — input gate, forget gate, cell
candidate, output gate — i.e. Keras order, so Keras HDF5 import is a direct
copy; the DL4J-zip importer permutes from DL4J's ordering.

Data layout matches the reference: activations [batch, features, time] (NCW).
Masking follows the ref's variable-length semantics: masked steps carry state
through unchanged and output zeros (ref: LSTMHelpers maskArray handling).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations as _act


def lstm_scan(
    x: jax.Array,  # [N, C, T]
    w: jax.Array,  # [C, 4H] gate order (i, f, c, o)
    rw: jax.Array,  # [H, 4H]
    b: jax.Array,  # [4H]
    h0: Optional[jax.Array] = None,  # [N, H]
    c0: Optional[jax.Array] = None,  # [N, H]
    peephole: Optional[jax.Array] = None,  # [3, H] rows (pI, pF, pO) — GravesLSTM
    mask: Optional[jax.Array] = None,  # [N, T]
    gate_act: str = "sigmoid",
    cell_act: str = "tanh",
    reverse: bool = False,
    use_pallas: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run an LSTM over the full sequence. Returns (out [N,H,T], hT, cT)."""
    n, _, t = x.shape
    h = rw.shape[0]
    gact = _act.get(gate_act)
    cact = _act.get(cell_act)

    if h0 is None:
        h0 = jnp.zeros((n, h), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((n, h), x.dtype)

    # Hoist the input projection out of the scan: one [T*N, C] @ [C, 4H] matmul.
    xt = jnp.transpose(x, (2, 0, 1))  # [T, N, C]
    zx = xt.reshape(t * n, -1) @ w
    zx = zx.reshape(t, n, 4 * h) + b

    # optional fused Pallas recurrence (cuDNN-fused-LSTM analog): keeps rw
    # and the (h,c) carry in VMEM across timesteps on TPU; gradients flow
    # through a custom_vjp that recomputes via scan. Same math — parity
    # tested against the scan path below. OFF by default: measured on a
    # real v5e chip (T=256, N=64, H=256) the per-timestep pallas grid
    # dispatch costs ~218us/step vs ~16us/step for XLA's scan (which
    # already keeps rw cached) — scan wins 14x. The kernel stays as the
    # opt-in reference implementation of the fused-RNN pattern.
    from deeplearning4j_tpu.nn.layers import pallas_kernels as _pk
    if use_pallas and _pk.pallas_lstm_supported(
            n, h, peephole=peephole, mask=mask, gate_act=gate_act,
            cell_act=cell_act):
        zxk = zx[::-1] if reverse else zx
        outs, h_fin, c_fin = _pk.lstm_recurrence(zxk, rw, h0, c0)
        if reverse:
            outs = outs[::-1]
        return jnp.transpose(outs, (1, 2, 0)), h_fin, c_fin

    if mask is not None:
        mt = jnp.transpose(mask, (1, 0))[:, :, None].astype(x.dtype)  # [T, N, 1]
    else:
        mt = None

    def step(carry, inputs):
        h_prev, c_prev = carry
        if mt is None:
            z_t = inputs
            m_t = None
        else:
            z_t, m_t = inputs
        z = z_t + h_prev @ rw
        zi, zf, zc, zo = jnp.split(z, 4, axis=-1)
        if peephole is not None:
            zi = zi + peephole[0] * c_prev
            zf = zf + peephole[1] * c_prev
        i = gact(zi)
        f = gact(zf)
        g = cact(zc)
        c_new = f * c_prev + i * g
        if peephole is not None:
            zo = zo + peephole[2] * c_new
        o = gact(zo)
        h_new = o * cact(c_new)
        if m_t is not None:
            h_new = h_new * m_t + h_prev * (1.0 - m_t)
            c_new = c_new * m_t + c_prev * (1.0 - m_t)
            out = h_new * m_t
        else:
            out = h_new
        return (h_new, c_new), out

    xs = zx if mt is None else (zx, mt)
    (h_fin, c_fin), outs = lax.scan(step, (h0, c0), xs, reverse=reverse)
    return jnp.transpose(outs, (1, 2, 0)), h_fin, c_fin


def bidirectional_sum(
    x, wf, rwf, bf, wb, rwb, bb, peep_f=None, peep_b=None, mask=None,
    gate_act="sigmoid", cell_act="tanh",
):
    """GravesBidirectionalLSTM: forward + backward LSTM outputs SUMMED."""
    out_f, _, _ = lstm_scan(x, wf, rwf, bf, peephole=peep_f, mask=mask,
                            gate_act=gate_act, cell_act=cell_act, reverse=False)
    out_b, _, _ = lstm_scan(x, wb, rwb, bb, peephole=peep_b, mask=mask,
                            gate_act=gate_act, cell_act=cell_act, reverse=True)
    return out_f + out_b


def simple_rnn_scan(x, w, rw, b, h0=None, mask=None, act="tanh"):
    """Vanilla RNN: h_t = act(x_t @ W + h_{t-1} @ RW + b)."""
    n, _, t = x.shape
    h = rw.shape[0]
    a = _act.get(act)
    if h0 is None:
        h0 = jnp.zeros((n, h), x.dtype)
    xt = jnp.transpose(x, (2, 0, 1))
    zx = xt.reshape(t * n, -1) @ w
    zx = zx.reshape(t, n, h) + b
    mt = None if mask is None else jnp.transpose(mask, (1, 0))[:, :, None].astype(x.dtype)

    def step(h_prev, inputs):
        if mt is None:
            z_t, m_t = inputs, None
        else:
            z_t, m_t = inputs
        h_new = a(z_t + h_prev @ rw)
        if m_t is not None:
            h_new = h_new * m_t + h_prev * (1.0 - m_t)
            return h_new, h_new * m_t
        return h_new, h_new

    xs = zx if mt is None else (zx, mt)
    h_fin, outs = lax.scan(step, h0, xs)
    return jnp.transpose(outs, (1, 2, 0)), h_fin
