"""Pallas TPU kernels: fused LSTM recurrence.

The reference accelerates LSTM through cuDNN's fused RNN path
(CudnnLSTMHelper.java:588 cudnnRNNForwardTraining — SURVEY §2.1), loaded as
an optional helper behind the composed implementation. This module is the
TPU analog: a Pallas kernel for the recurrent half of the LSTM that keeps
the [H,4H] recurrent weights and the (h, c) carry resident in VMEM across
ALL timesteps (grid iterations on TPU run sequentially on one core, so VMEM
scratch persists), instead of the scan-based path where each iteration
re-reads weights from HBM.

Like the reference's helper hook (ConvolutionLayer.java:74-84 reflective
load), the kernel is optional: `lstm_recurrence` falls back to lax.scan
when shapes/dtypes don't meet the TPU tiling constraints (H % 128, N % 8)
or when running on CPU (where it uses the Pallas interpreter only under
test). Parity with the scan path is covered by tests mirroring
ValidateCudnnLSTM.java (SURVEY §4 backend-vs-backend pattern).

Measured on a real v5e chip (T=256, N=64, H=256): outputs match scan
exactly (0.0 max diff), but the per-timestep grid dispatch costs
~218us/step against ~16us/step for XLA's scan — scan wins ~14x, because
XLA already keeps the [H,4H] recurrent weights cached across scan
iterations and pipelines the carry. lstm_scan therefore defaults to the
scan path (use_pallas=False); this kernel remains the opt-in reference
for the fused-RNN pattern.

Gate order matches nn/layers/recurrent.py: (i, f, c, o).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _lstm_kernel(zx_ref, rw_ref, h0_ref, c0_ref,
                 out_ref, hT_ref, cT_ref, h_scr, c_scr, *, t_total: int):
    """One grid step = one timestep. zx_ref: [N,4H] (input projection +
    bias, precomputed), rw_ref: [H,4H] resident across steps, scratch
    carries (h, c) in fp32."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h_prev = h_scr[:]
    gates = zx_ref[0].astype(jnp.float32) + \
        jax.lax.dot(h_prev, rw_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    hdim = h_prev.shape[-1]
    i = jax.nn.sigmoid(gates[:, 0 * hdim:1 * hdim])
    f = jax.nn.sigmoid(gates[:, 1 * hdim:2 * hdim])
    g = jnp.tanh(gates[:, 2 * hdim:3 * hdim])
    o = jax.nn.sigmoid(gates[:, 3 * hdim:4 * hdim])
    c = f * c_scr[:] + i * g
    h = o * jnp.tanh(c)
    h_scr[:] = h
    c_scr[:] = c
    out_ref[0] = h.astype(out_ref.dtype)

    @pl.when(t == t_total - 1)
    def _final():
        hT_ref[:] = h.astype(hT_ref.dtype)
        cT_ref[:] = c.astype(cT_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_lstm_recurrence(zx: jax.Array, rw: jax.Array, h0: jax.Array,
                           c0: jax.Array, interpret: bool = False
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused LSTM recurrence.

    zx: [T, N, 4H] input projections (x@W + b for every step, computed as
        one big MXU matmul outside), rw: [H, 4H], h0/c0: [N, H].
    Returns (out [T, N, H], hT [N, H], cT [N, H]).
    """
    t, n, four_h = zx.shape
    h = four_h // 4
    kernel = functools.partial(_lstm_kernel, t_total=t)
    out, hT, cT = pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, n, four_h), lambda i: (i, 0, 0)),   # zx step i
            pl.BlockSpec((h, four_h), lambda i: (0, 0)),         # rw resident
            pl.BlockSpec((n, h), lambda i: (0, 0)),              # h0
            pl.BlockSpec((n, h), lambda i: (0, 0)),              # c0
        ],
        out_specs=[
            pl.BlockSpec((1, n, h), lambda i: (i, 0, 0)),        # out step i
            pl.BlockSpec((n, h), lambda i: (0, 0)),              # hT
            pl.BlockSpec((n, h), lambda i: (0, 0)),              # cT
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, h), zx.dtype),
            jax.ShapeDtypeStruct((n, h), zx.dtype),
            jax.ShapeDtypeStruct((n, h), zx.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, h), jnp.float32),   # h carry
            pltpu.VMEM((n, h), jnp.float32),   # c carry
        ],
        interpret=interpret,
    )(zx, rw, h0, c0)
    return out, hT, cT


def pallas_lstm_supported(n: int, h: int, *, peephole, mask, gate_act: str,
                          cell_act: str) -> bool:
    """Static eligibility: standard gates, no peephole/mask, tile-friendly
    shapes (TPU tiling: lanes of 128, sublanes of 8)."""
    if peephole is not None or mask is not None:
        return False
    if gate_act != "sigmoid" or cell_act != "tanh":
        return False
    if h % 128 != 0 or n % 8 != 0:
        return False
    return True


def _scan_recurrence(zx, rw, h0, c0):
    """Pure-JAX recurrence with identical math — the AD path and the
    non-TPU fallback."""
    hdim = rw.shape[0]

    def step(carry, z):
        h_prev, c_prev = carry
        g = z + h_prev @ rw
        i = jax.nn.sigmoid(g[:, :hdim])
        f = jax.nn.sigmoid(g[:, hdim:2 * hdim])
        cc = jnp.tanh(g[:, 2 * hdim:3 * hdim])
        o = jax.nn.sigmoid(g[:, 3 * hdim:])
        c = f * c_prev + i * cc
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), outs = jax.lax.scan(step, (h0, c0), zx)
    return outs, hT, cT


@jax.custom_vjp
def lstm_recurrence(zx, rw, h0, c0):
    """Fused LSTM recurrence with autodiff support: forward runs the
    Pallas kernel on TPU (scan elsewhere); backward recomputes through the
    scan implementation (Pallas grid-carried VMEM scratch has no
    reverse-mode rule — custom_vjp hides the kernel from AD)."""
    if jax.default_backend() == "tpu":
        return pallas_lstm_recurrence(zx, rw, h0, c0)
    return _scan_recurrence(zx, rw, h0, c0)


def _lstm_fwd(zx, rw, h0, c0):
    return lstm_recurrence(zx, rw, h0, c0), (zx, rw, h0, c0)


def _lstm_bwd(res, grads):
    zx, rw, h0, c0 = res
    _, vjp = jax.vjp(_scan_recurrence, zx, rw, h0, c0)
    return vjp(grads)


lstm_recurrence.defvjp(_lstm_fwd, _lstm_bwd)
