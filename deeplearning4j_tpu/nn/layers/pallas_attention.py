"""Pallas TPU flash attention: fused multi-head attention kernel.

The framework's long-context attention hot op. The lax.scan blockwise path
(parallel/sequence.py blockwise_attention) is exact but leaves perf on the
table: every scan step computes scores for ALL T queries against one KV
block (no query blocking), fully-masked causal blocks are still computed,
and the accumulators round-trip through HBM between steps. This kernel is
the standard flash-attention schedule on the TPU memory hierarchy:

- grid (B, H, nq, nk), KV innermost: the [bq, D] query block and the
  (m, l, acc) online-softmax state live in VMEM scratch across all KV
  steps — one HBM read per Q/K/V block, one HBM write per output block.
- causal blocks strictly above the diagonal are skipped (roughly 2x for
  long causal sequences), and in-block masking handles the diagonal.
- blocks that need no masking at all (fully below the diagonal, no key
  padding, no user mask) take a fast path with zero mask VPU ops — the
  exp is the VPU bottleneck, so iota/compare/select per score matter.
- QK^T / PV matmuls run on the MXU in the input dtype (bf16) with fp32
  accumulation; softmax statistics are fp32 throughout.
- backward is the recompute form (Dao et al. 2022): forward saves only
  the [B,H,T] logsumexp; dq and dk/dv kernels rebuild the probabilities
  per block — the same memory profile the cuDNN fused-attention path
  gives the reference's GPU stack (SURVEY §2.1 fused-op parity row).

Layout [B, H, T, D], same as parallel/sequence.py. Exactness vs
reference_attention is covered by tests/test_pallas_attention.py; the
real-chip numbers live in PERF.md.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # finite: (-inf) - (-inf) = nan inside exp would poison rows

LOG2E = float(np.log2(np.e))   # fwd runs the online softmax in base 2:
LN2 = float(np.log(2.0))       # exp2((s-m)*log2e) == exp(s-m) exactly, but
#                                exp2 skips the VPU's internal x*log2e step
#                                (one multiply per score); lse converts back
#                                to natural log at the block boundary

# 1024/1024 measured fastest on v5e at T=8k/D=128 (sweep in PERF.md)
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _causal_needed(i, j, bq, bk, window=None, q_offset=0):
    """Is KV block j visible to any query in Q block i? (block-skip test:
    causal upper bound, plus the sliding-window lower bound when set).
    `q_offset` (static) shifts query positions — ring attention runs past
    KV chunks as banded attention with q_offset = chunk distance."""
    q0 = q_offset + i * bq
    needed = q0 + bq - 1 >= j * bk
    if window is not None:
        # some key in the block is within (q - window, q] for some query
        needed = jnp.logical_and(needed,
                                 j * bk + bk - 1 > q0 - window)
    return needed


def _block_mask(i, j, bq, bk, causal: bool, kmask_row, window=None,
                q_offset=0):
    """[bq, bk] validity mask for one (Q block, KV block) pair.
    kmask_row: [1, bk]."""
    valid = jnp.broadcast_to(kmask_row.astype(bool), (bq, bk))
    if causal:
        q_pos = (q_offset + i * bq
                 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
        k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = valid & (q_pos >= k_pos)
        if window is not None:
            valid = valid & (q_pos - k_pos < window)
    return valid


def _dispatch(i, j, fast_fn, masked_fn, *, causal, bq, bk, nk,
              first_pad, user_mask, window=None, q_offset=0):
    """Run the fast (no mask VPU ops) or masked block body.

    Masking is needed only for diagonal-straddling causal blocks, blocks
    straddling a sliding-window edge, KV blocks containing padded keys
    (j >= first_pad — padding can span multiple tail blocks when the
    block sizes differ), or when a user key mask exists (then always).
    Blocks fully above the causal diagonal or fully OUTSIDE the window
    are skipped entirely — with `window` set, cost is O(T*W)."""
    if user_mask:
        if causal:
            pl.when(_causal_needed(i, j, bq, bk, window,
                                   q_offset))(masked_fn)
        else:
            masked_fn()
        return
    tail = (j >= first_pad) if first_pad is not None else None
    if causal:
        needed = _causal_needed(i, j, bq, bk, window, q_offset)
        q0 = q_offset + i * bq
        interior = q0 >= j * bk + bk - 1       # no in-block causal mask
        if window is not None:
            # every pair also inside the window: max(q) - min(k) < W
            interior = jnp.logical_and(
                interior, q0 + bq - 1 - j * bk < window)
        fast = jnp.logical_and(needed, interior)
        if tail is not None:
            fast = jnp.logical_and(fast, jnp.logical_not(tail))
        pl.when(fast)(fast_fn)
        pl.when(jnp.logical_and(needed, jnp.logical_not(fast)))(masked_fn)
    elif tail is None:
        fast_fn()
    else:
        pl.when(jnp.logical_not(tail))(fast_fn)
        pl.when(tail)(masked_fn)


def _fwd_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                acc_scr, m_scr, l_scr, *, scale, causal, bq, bk, nk,
                first_pad, user_mask, window=None, q_offset=0):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute(masked: bool):
        # scores in BASE-2 units (scale folds in log2(e)); p values are
        # bit-for-bit the same softmax weights, m/l carry base-2 maxima
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * LOG2E)
        if masked:
            valid = _block_mask(i, j, bq, bk, causal, km_ref[0], window,
                                q_offset)
            s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scr[:][:, :1]                               # [bq, 1]
        l_prev = l_scr[:][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        if masked:
            # explicit zeroing: if a whole row is masked,
            # exp2(NEG_INF - NEG_INF) would be 1 — keep such rows at p=0
            p = p * valid.astype(jnp.float32)
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bq, D]
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _dispatch(i, j, lambda: _compute(False), lambda: _compute(True),
              causal=causal, bq=bq, bk=bk, nk=nk, first_pad=first_pad,
              user_mask=user_mask, window=window, q_offset=q_offset)

    @pl.when(j == nk - 1)
    def _finish():
        m = m_scr[:][:, :1]                    # base-2 running max
        l = l_scr[:][:, :1]
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # public lse stays NATURAL log (backward + ring combine contract)
        lse_ref[0, 0] = m * LN2 + jnp.log(jnp.maximum(l, 1e-30))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, d_ref,
                   dq_ref, dq_scr, *, scale, causal, bq, bk, nk,
                   first_pad, user_mask, window=None, q_offset=0):
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute(masked: bool):
        # base-2 probabilities like the forward: exp2(s*log2e - lse*log2e)
        # == exp(s - lse); ds keeps the NATURAL scale (chain rule)
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * LOG2E)
        if masked:
            # mask BEFORE exp (as forward does): a masked raw score above
            # the row lse would overflow exp to inf and 0*inf = NaN
            valid = _block_mask(i, j, bq, bk, causal, km_ref[0], window,
                                q_offset)
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp2(s - lse_ref[0, 0] * LOG2E)
        if masked:
            p = p * valid.astype(jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bq, bk]
        ds = p * (dp - d_ref[0, 0]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _dispatch(i, j, lambda: _compute(False), lambda: _compute(True),
              causal=causal, bq=bq, bk=bk, nk=nk, first_pad=first_pad,
              user_mask=user_mask, window=window, q_offset=q_offset)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, km_ref, do_ref, lse_ref, d_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale, causal, bq, bk, nq, nk,
                    first_pad, user_mask, window=None, q_offset=0):
    j, i = pl.program_id(2), pl.program_id(3)   # Q innermost here

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute(masked: bool):
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * (scale * LOG2E)
        if masked:
            valid = _block_mask(i, j, bq, bk, causal, km_ref[0], window,
                                q_offset)
            s = jnp.where(valid, s, NEG_INF)   # see _bwd_dq_kernel note
        p = jnp.exp2(s - lse_ref[0, 0] * LOG2E)
        if masked:
            p = p * valid.astype(jnp.float32)
        pt = p.astype(do_ref.dtype)
        dv_scr[:] += jax.lax.dot_general(
            pt, do_ref[0, 0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, D]
        dp = jax.lax.dot_general(
            do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - d_ref[0, 0]) * scale).astype(q_ref.dtype)
        dk_scr[:] += jax.lax.dot_general(
            ds, q_ref[0, 0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [bk, D]

    _dispatch(i, j, lambda: _compute(False), lambda: _compute(True),
              causal=causal, bq=bq, bk=bk, nk=nk, first_pad=first_pad,
              user_mask=user_mask, window=window, q_offset=q_offset)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _qkv_spec(bq_or_bk, D, axis):
    """Block spec for q/k/v: (1,1,block,D), selecting grid axis 2 or 3."""
    if axis == 2:
        return pl.BlockSpec((1, 1, bq_or_bk, D),
                            lambda b, h, i, j: (b, h, i, 0))
    return pl.BlockSpec((1, 1, bq_or_bk, D),
                        lambda b, h, i, j: (b, h, j, 0))


def _row_spec(block, axis):
    """Block spec for per-row stats [B,H,T,1]: (1,1,block,1) — trailing
    dim 1 satisfies the Mosaic tiling rule (block dim == array dim)."""
    if axis == 2:
        return pl.BlockSpec((1, 1, block, 1), lambda b, h, i, j: (b, h, i, 0))
    return pl.BlockSpec((1, 1, block, 1), lambda b, h, i, j: (b, h, j, 0))


def _km_spec(bk, axis):
    """Block spec for the key mask [B,1,T]: (1,1,bk), KV-indexed."""
    if axis == 3:
        return pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j))
    return pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, i))


def _pad_t(x, bs):
    pad = (-x.shape[2]) % bs
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x



def _run_bwd_kernels(q, k, v, key_mask, do, lse, d_eff, *, causal, bq, bk,
                     first_pad, user_mask, interpret, window=None,
                     q_offset=0):
    """The dq and dk/dv pallas calls shared by both VJPs. `d_eff` sits in
    the delta slot: plain backward passes delta = rowsum(do*o); the
    lse-differentiable variant passes delta - dlse. Query and key lengths
    are independent (cross-/chunked attention)."""
    B, H, T, D = q.shape
    Tk = k.shape[2]
    scale = float(1.0 / np.sqrt(D))
    nq, nk = T // bq, Tk // bk

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, first_pad=first_pad,
                          user_mask=user_mask, window=window,
                          q_offset=q_offset),
        grid=(B, H, nq, nk),
        in_specs=[_qkv_spec(bq, D, 2), _qkv_spec(bk, D, 3),
                  _qkv_spec(bk, D, 3), _km_spec(bk, 3),
                  _qkv_spec(bq, D, 2), _row_spec(bq, 2), _row_spec(bq, 2)],
        out_specs=_qkv_spec(bq, D, 2),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, key_mask, do, lse, d_eff)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nk=nk, first_pad=first_pad,
                          user_mask=user_mask, window=window,
                          q_offset=q_offset),
        # KV block is the carried axis; Q innermost
        grid=(B, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk), lambda b, h, j, i: (b, 0, j)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((B, H, Tk, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Tk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, key_mask, do, lse, d_eff)
    return dq, dk, dv


def _flash_fwd(q, k, v, key_mask, causal, bq, bk, first_pad, user_mask,
               interpret, window=None, q_offset=0):
    B, H, T, D = q.shape
    scale = float(1.0 / np.sqrt(D))
    nq, nk = T // bq, k.shape[2] // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, first_pad=first_pad,
                               user_mask=user_mask, window=window,
                               q_offset=q_offset)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[_qkv_spec(bq, D, 2), _qkv_spec(bk, D, 3),
                  _qkv_spec(bk, D, 3), _km_spec(bk, 3)],
        out_specs=[_qkv_spec(bq, D, 2), _row_spec(bq, 2)],
        out_shape=[jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
                   jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32)],
        interpret=interpret,
    )(q, k, v, key_mask)
    return o, (q, k, v, key_mask, o, lse)


# -- (o, lse) variant: for cross-chunk combination (ring attention) --------
#
# Exposing the logsumexp differentiably costs one line of math:
# d lse_i / d s_ij = p_ij, so the score cotangent becomes
# ds = p * (dp - delta + dlse) = p * (dp - (delta - dlse)) — the existing
# backward kernels run unchanged with d_eff = delta - dlse in the delta
# slot (dv is independent of lse).


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash_lse(q, k, v, key_mask, causal, bq, bk, first_pad, user_mask,
               interpret, window, q_offset):
    (o, lse), _ = _flash_lse_fwd(q, k, v, key_mask, causal, bq, bk,
                                 first_pad, user_mask, interpret, window,
                                 q_offset)
    return o, lse


def _flash_lse_fwd(q, k, v, key_mask, causal, bq, bk, first_pad, user_mask,
                   interpret, window, q_offset):
    o, res = _flash_fwd(q, k, v, key_mask, causal, bq, bk, first_pad,
                        user_mask, interpret, window, q_offset)
    lse = res[-1]
    return (o, lse), res


def _flash_lse_bwd(causal, bq, bk, first_pad, user_mask, interpret, window,
                   q_offset, res, cotangents):
    do, dlse = cotangents
    q, k, v, key_mask, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    d_eff = delta - dlse.astype(jnp.float32)
    dq, dk, dv = _run_bwd_kernels(q, k, v, key_mask, do, lse, d_eff,
                                  causal=causal, bq=bq, bk=bk,
                                  first_pad=first_pad, user_mask=user_mask,
                                  interpret=interpret, window=window,
                                  q_offset=q_offset)
    return dq, dk, dv, jnp.zeros_like(key_mask)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q, k, v, causal: bool = False, key_mask=None,
                        block_q: int = DEFAULT_BLOCK_Q,
                        block_k: int = DEFAULT_BLOCK_K,
                        interpret: bool = False,
                        window: Optional[int] = None,
                        q_offset: int = 0):
    """Like flash_attention but also returns the per-row logsumexp
    [B,H,Tq] (fp32) — differentiable through both outputs, for combining
    attention over KV chunks (ring attention: merge (o_i, lse_i) pairs
    with the standard logaddexp rule).

    `q_offset` (static int) shifts query positions for the causal/window
    masks: windowed ring attention runs a PAST chunk as banded attention
    with q_offset = (global query start) - (global key start); blocks
    outside the band are skipped, so a mostly-out-of-window chunk costs
    almost nothing."""
    if window is not None and not causal:
        raise ValueError("window attention requires causal=True")
    if q_offset and not causal:
        raise ValueError("q_offset only shifts the causal/window masks; "
                         "it requires causal=True")
    q, k, v, km, bq, bk, first_pad, user_mask, Tq = _prep(
        q, k, v, key_mask, causal, block_q, block_k,
        allow_unaligned_causal=q_offset != 0)
    o, lse = _flash_lse(q, k, v, km, causal, bq, bk, first_pad, user_mask,
                        interpret, window, int(q_offset))
    return o[:, :, :Tq, :], lse[:, :, :Tq, 0]


def _prep(q, k, v, key_mask, causal, block_q, block_k,
          allow_unaligned_causal=False):
    """Pad q to a block_q multiple and k/v to a block_k multiple
    (independently — Tq need not equal Tk for non-causal / chunked use),
    build the padded-key mask, and pick tile-aligned block sizes."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    if causal and not allow_unaligned_causal and Tq != Tk:
        raise ValueError("causal flash attention needs Tq == Tk "
                         f"(got {Tq} vs {Tk})")
    bq = int(min(block_q, ((Tq + 127) // 128) * 128))
    bk = int(min(block_k, ((Tk + 127) // 128) * 128))
    q = _pad_t(q, bq)
    k, v = _pad_t(k, bk), _pad_t(v, bk)
    Tkp = k.shape[2]
    first_pad = (Tk // bk) if Tkp != Tk else None
    user_mask = key_mask is not None
    if key_mask is None:
        km = (jnp.arange(Tkp) < Tk).astype(jnp.float32)[None, None, :]
        km = jnp.broadcast_to(km, (B, 1, Tkp))
    else:
        km = key_mask.astype(jnp.float32)[:, None, :]
        km = jnp.pad(km, ((0, 0), (0, 0), (0, Tkp - km.shape[2])))
    return q, k, v, km, bq, bk, first_pad, user_mask, Tq


def flash_attention_supported(q_shape: Tuple[int, ...],
                              block_q: int = DEFAULT_BLOCK_Q,
                              block_k: int = DEFAULT_BLOCK_K) -> bool:
    """Shape gate (mirrors pallas_lstm_supported's role): head dim must be
    lane-tileable and T large enough to block."""
    if len(q_shape) != 4:
        return False
    _, _, T, D = q_shape
    return D in (64, 128, 256) and T >= 128


def flash_attention(q, k, v, causal: bool = False, key_mask=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False,
                    window: Optional[int] = None):
    """Fused flash attention. q: [B,H,Tq,D]; k,v: [B,H,Tk,D]; key_mask:
    [B,Tk] (1=valid). Tq and Tk may differ (cross-/chunked attention)
    except under causal, which requires aligned lengths.

    Lengths are padded internally to block multiples (padded keys masked
    out, padded query rows sliced off). Differentiable via the
    recompute-form custom VJP. Use `interpret=True` on CPU (tests)."""
    if window is not None and not causal:
        raise ValueError("window attention requires causal=True")
    q, k, v, km, bq, bk, first_pad, user_mask, Tq = _prep(
        q, k, v, key_mask, causal, block_q, block_k)
    # single custom_vjp serves both entry points: when the lse output is
    # unused JAX feeds a zeros cotangent, so d_eff = delta - 0 = delta
    out, _ = _flash_lse(q, k, v, km, causal, bq, bk, first_pad, user_mask,
                        interpret, window, 0)
    return out[:, :, :Tq, :]
