"""Normalization ops: batch norm + local response normalization.

TPU-native equivalent of:
- CudnnBatchNormalizationHelper (deeplearning4j-cuda/.../normalization/
  CudnnBatchNormalizationHelper.java:45-234) and BatchNormalization.java —
  fused by XLA; running mean/var are explicit state (pytree), replacing the
  ref's mutable param-view entries.
- CudnnLocalResponseNormalizationHelper (.../CudnnLocalResponseNormalizationHelper.java)
  — composed from pad+reduce_window; XLA fuses the window sum into the
  normalization arithmetic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def batch_norm(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    train: bool,
    eps: float = 1e-5,
    decay: float = 0.9,
    channel_axis: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Batch normalization over all-but-channel axes.

    x is [N,F] (channel=axis 1), [N,C,H,W] (channel=axis 1, DL4J NCHW), or
    [N,H,W,C] with channel_axis=3 (internal NHWC mode — channel-minor keeps
    the per-channel stat reductions lane-aligned on the TPU VPU).
    Returns (y, new_running_mean, new_running_var). Running stats update uses
    the reference's decay semantics: new = decay*old + (1-decay)*batch
    (ref: BatchNormalization.java `decay` field, default 0.9).
    """
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    bshape = [1] * x.ndim
    bshape[channel_axis] = x.shape[channel_axis]

    if train:
        # one-pass stats: E[x] and E[x^2] fuse into a single read of x
        # (vs. jnp.var's subtract-mean second pass — on TPU the big
        # activation tensors are HBM-bandwidth-bound, so one fewer pass
        # is a direct win). Accumulate in >=fp32 under mixed precision.
        acc_t = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(acc_t)
        mean = jnp.mean(xf, axis=axes)
        # clamp: E[x^2]-mean^2 can round negative in fp32 when |mean| is
        # large and true variance tiny, which would NaN the rsqrt below
        var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
        new_mean = decay * running_mean + (1.0 - decay) * mean
        new_var = decay * running_var + (1.0 - decay) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var

    inv = lax.rsqrt(var + eps).astype(x.dtype)
    y = (x - mean.astype(x.dtype).reshape(bshape)) * inv.reshape(bshape)
    y = y * gamma.reshape(bshape) + beta.reshape(bshape)
    return y, new_mean, new_var


def lrn(
    x: jax.Array,
    k: float = 2.0,
    n: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    channel_axis: int = 1,
) -> jax.Array:
    """Local response normalization across channels (ref: LocalResponseNormalization
    layer, defaults k=2 n=5 alpha=1e-4 beta=0.75).

    y = x / (k + alpha * sum_{j in window n} x_j^2)^beta, window centered per channel.
    """
    sq = x * x
    half = n // 2
    # window-sum across the channel axis via reduce_window
    wd = [1, 1, 1, 1]
    wd[channel_axis] = n
    pads = [(0, 0)] * 4
    pads[channel_axis] = (half, half)
    win = lax.reduce_window(
        sq,
        0.0,
        lax.add,
        window_dimensions=tuple(wd),
        window_strides=(1, 1, 1, 1),
        padding=pads,
    )
    return x / (k + alpha * win) ** beta
