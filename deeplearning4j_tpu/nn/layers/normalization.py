"""Normalization ops: batch norm + local response normalization.

TPU-native equivalent of:
- CudnnBatchNormalizationHelper (deeplearning4j-cuda/.../normalization/
  CudnnBatchNormalizationHelper.java:45-234) and BatchNormalization.java —
  fused by XLA; running mean/var are explicit state (pytree), replacing the
  ref's mutable param-view entries.
- CudnnLocalResponseNormalizationHelper (.../CudnnLocalResponseNormalizationHelper.java)
  — composed from pad+reduce_window; XLA fuses the window sum into the
  normalization arithmetic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def batch_norm(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    train: bool,
    eps: float = 1e-5,
    decay: float = 0.9,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Batch normalization over all-but-channel axes.

    x is [N,F] (channel=axis 1) or [N,C,H,W] (channel=axis 1, DL4J NCHW).
    Returns (y, new_running_mean, new_running_var). Running stats update uses
    the reference's decay semantics: new = decay*old + (1-decay)*batch
    (ref: BatchNormalization.java `decay` field, default 0.9).
    """
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = [1] * x.ndim
    bshape[1] = x.shape[1]

    if train:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_mean = decay * running_mean + (1.0 - decay) * mean
        new_var = decay * running_var + (1.0 - decay) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var

    inv = lax.rsqrt(var + eps)
    y = (x - mean.reshape(bshape)) * inv.reshape(bshape)
    y = y * gamma.reshape(bshape) + beta.reshape(bshape)
    return y, new_mean, new_var


def lrn(
    x: jax.Array,
    k: float = 2.0,
    n: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
) -> jax.Array:
    """Local response normalization across channels (ref: LocalResponseNormalization
    layer, defaults k=2 n=5 alpha=1e-4 beta=0.75).

    y = x / (k + alpha * sum_{j in window n} x_j^2)^beta, window centered per channel.
    """
    sq = x * x
    half = n // 2
    # window-sum across the channel axis via reduce_window
    win = lax.reduce_window(
        sq,
        0.0,
        lax.add,
        window_dimensions=(1, n, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=[(0, 0), (half, half), (0, 0), (0, 0)],
    )
    return x / (k + alpha * win) ** beta
