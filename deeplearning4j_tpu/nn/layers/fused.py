"""Fused BatchNorm→activation→1×1-convolution (the ResNet bottleneck hot
path).

Why: the ResNet50 profile (PERF.md) shows the step is HBM-bandwidth-bound
on BatchNorm traffic, not MXU-bound — the normalize pass writes a full
activation tensor that the next conv immediately re-reads. For the
bn→relu→1×1-conv chains inside bottleneck blocks (the only place the
normalized tensor has a single consumer), the normalize+activation can be
a *prologue* of the next conv instead: read the raw conv output once,
normalize on the fly in VMEM, and feed the MXU directly. A 1×1 conv is a
channel matmul, so the fused op is `act(y∘a + b) @ W` with per-channel
affine (a, b) folded from the batch-norm statistics.

This out-engineers the reference's fused cuDNN path
(deeplearning4j-cuda/.../convolution/CudnnConvolutionHelper.java:54-480,
CudnnBatchNormalizationHelper.java:45-234): cuDNN fuses bias+activation
into the conv epilogue; here the whole BN-apply rides the conv prologue
and the backward recomputes the normalized tensor instead of storing it.

Two implementations behind one interface:
- a Pallas TPU kernel (`use_pallas=True`): forward reads y once per
  output tile; the backward is ONE pass over (y, g) producing dy and
  accumulating dW, d(scale), d(bias) in VMEM scratch — replacing the
  separate relu-mask read, two BN reductions, and dW matmul read that
  autodiff of the unfused chain issues.
- a jnp formulation (fallback/CPU): the same math as dot_general, which
  XLA can fuse the affine prologue into.

Batch statistics (E[x], E[x²] one-pass, fp32) and the running-stat decay
stay in jnp — they are a reduction XLA fuses well, and keeping them
outside the custom_vjp lets autodiff carry the BN stats backward chain
(d mean/d var contributions to dy) automatically.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.nn.activations import get as _get_act

#: rows per grid step; full C (contraction) and K (output channels) stay
#: resident — bottleneck shapes are C<=512, K<=2048, so W + a [bm,K] fp32
#: tile fit VMEM comfortably
DEFAULT_BLOCK_M = int(os.environ.get("DL4JTPU_FUSED_BM", "256"))

_SUPPORTED_ACTS = ("identity", "relu")


def fused_conv1x1_supported(C: int, K: int, act: str) -> bool:
    """Shape/activation gate for the Pallas path: the kernel keeps the
    whole [C, K] weight and a [block_m, K] fp32 accumulator in VMEM."""
    return act in _SUPPORTED_ACTS and C * K <= 512 * 2048 and K <= 4096


def _pick_bm(M: int) -> int:
    for bm in (DEFAULT_BLOCK_M, 128, 64, 32, 16, 8):
        if M % bm == 0:
            return bm
    return DEFAULT_BLOCK_M  # non-divisible: kernel masks the tail rows


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(y_ref, sc_ref, bb_ref, w_ref, b_ref, o_ref, *, act):
    z = y_ref[...].astype(jnp.float32) * sc_ref[...] + bb_ref[...]
    if act == "relu":
        z = jnp.maximum(z, 0.0)
    out = lax.dot_general(z.astype(w_ref.dtype), w_ref[...],
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    o_ref[...] = (out + b_ref[...]).astype(o_ref.dtype)


def _bwd_kernel(y_ref, sc_ref, bb_ref, w_ref, g_ref,
                dy_ref, dsc_ref, dbb_ref, dw_ref, db_ref,
                dw_scr, dsc_scr, dbb_scr, db_scr,
                *, act, nm, bm, M):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)
        dsc_scr[...] = jnp.zeros_like(dsc_scr)
        dbb_scr[...] = jnp.zeros_like(dbb_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    yf = y_ref[...].astype(jnp.float32)                     # [bm, C]
    g = g_ref[...]                                          # [bm, K]
    if M % bm:
        # tail block: rows beyond M are garbage loads (possibly inf/nan)
        # — select them to zero out of every reduction and of the dz
        # that feeds dy (stores are masked by Pallas, but the scratch
        # accumulators are not; 0*garbage would still be nan)
        row = i * bm + lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        valid = row < M
        yf = jnp.where(valid, yf, 0.0)
        g = jnp.where(valid, g, jnp.zeros((), g.dtype))
    z0 = yf * sc_ref[...] + bb_ref[...]
    z = jnp.maximum(z0, 0.0) if act == "relu" else z0
    if M % bm:
        z = jnp.where(valid, z, 0.0)
    dz = lax.dot_general(g, w_ref[...], (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)  # [bm, C]
    if act == "relu":
        dz = jnp.where(z0 > 0, dz, 0.0)
    if M % bm:
        dz = jnp.where(valid, dz, 0.0)
    dy_ref[...] = (dz * sc_ref[...]).astype(dy_ref.dtype)
    dw_scr[...] += lax.dot_general(z.astype(g.dtype), g,
                                   (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    dsc_scr[...] += jnp.sum(dz * yf, axis=0, keepdims=True)
    dbb_scr[...] += jnp.sum(dz, axis=0, keepdims=True)
    db_scr[...] += jnp.sum(g.astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(i == nm - 1)
    def _finish():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)
        dsc_ref[...] = dsc_scr[...]
        dbb_ref[...] = dbb_scr[...]
        db_ref[...] = db_scr[...]


def _row_block(bm, C):
    return pl.BlockSpec((bm, C), lambda i: (i, 0))


def _full_spec(r, c):
    return pl.BlockSpec((r, c), lambda i: (0, 0))


def _pallas_fwd(y2, sc, bb, w2, b, act, bm, interpret):
    M, C = y2.shape
    K = w2.shape[1]
    nm = -(-M // bm)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, act=act),
        grid=(nm,),
        in_specs=[_row_block(bm, C), _full_spec(1, C), _full_spec(1, C),
                  _full_spec(C, K), _full_spec(1, K)],
        out_specs=_row_block(bm, K),
        out_shape=jax.ShapeDtypeStruct((M, K), y2.dtype),
        scratch_shapes=[],
        interpret=interpret,
    )(y2, sc[None, :], bb[None, :], w2, b[None, :])


def _pallas_bwd(y2, sc, bb, w2, g, act, bm, interpret):
    M, C = y2.shape
    K = w2.shape[1]
    nm = -(-M // bm)
    dy, dsc, dbb, dw, db = pl.pallas_call(
        functools.partial(_bwd_kernel, act=act, nm=nm, bm=bm, M=M),
        grid=(nm,),
        in_specs=[_row_block(bm, C), _full_spec(1, C), _full_spec(1, C),
                  _full_spec(C, K), _row_block(bm, K)],
        out_specs=[_row_block(bm, C), _full_spec(1, C), _full_spec(1, C),
                   _full_spec(C, K), _full_spec(1, K)],
        out_shape=[jax.ShapeDtypeStruct((M, C), y2.dtype),
                   jax.ShapeDtypeStruct((1, C), jnp.float32),
                   jax.ShapeDtypeStruct((1, C), jnp.float32),
                   jax.ShapeDtypeStruct((C, K), w2.dtype),
                   jax.ShapeDtypeStruct((1, K), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((C, K), jnp.float32),
                        pltpu.VMEM((1, C), jnp.float32),
                        pltpu.VMEM((1, C), jnp.float32),
                        pltpu.VMEM((1, K), jnp.float32)],
        interpret=interpret,
    )(y2, sc[None, :], bb[None, :], w2, g)
    return dy, dsc[0], dbb[0], dw, db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused_matmul_pallas(y2, sc, bb, w2, b, act, bm, interpret):
    """act(y2 ∘ sc + bb) @ w2 + b with a Pallas forward and a one-pass
    Pallas backward. y2: [M, C]; sc/bb: [C] fp32; w2: [C, K]; b: [K]."""
    out, _ = _fused_matmul_fwd(y2, sc, bb, w2, b, act, bm, interpret)
    return out


def _fused_matmul_fwd(y2, sc, bb, w2, b, act, bm, interpret):
    out = _pallas_fwd(y2, sc, bb, w2, b, act, bm, interpret)
    return out, (y2, sc, bb, w2)


def _fused_matmul_bwd(act, bm, interpret, res, g):
    y2, sc, bb, w2 = res
    dy, dsc, dbb, dw, db = _pallas_bwd(y2, sc, bb, w2, g, act, bm,
                                       interpret)
    return dy, dsc, dbb, dw, db


_fused_matmul_pallas.defvjp(_fused_matmul_fwd, _fused_matmul_bwd)


def _fused_matmul_ref(y2, sc, bb, w2, b, act):
    """jnp formulation (autodiff backward); same contract as the kernel.
    Accumulation dtype follows sc (>= fp32; fp64 under x64 inputs)."""
    z = y2.astype(sc.dtype) * sc[None, :] + bb[None, :]
    if act == "relu":
        z = jnp.maximum(z, 0.0)
    elif act != "identity":
        z = _get_act(act)(z)
    out = lax.dot_general(z.astype(w2.dtype), w2, (((1,), (0,)), ((), ())),
                          preferred_element_type=sc.dtype)
    return (out + b[None, :]).astype(y2.dtype)


# ---------------------------------------------------------------------------
# full bn→act→conv1x1 semantics
# ---------------------------------------------------------------------------


def bn_act_conv1x1(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    w: jax.Array,
    b: Optional[jax.Array],
    *,
    train: bool,
    eps: float = 1e-5,
    decay: float = 0.9,
    act: str = "relu",
    data_format: str = "NCHW",
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """BatchNorm → activation → 1×1 conv (stride 1, no padding) in one op.

    x: the RAW preceding conv output [N,C,H,W] or [N,H,W,C]; w: [O,I,1,1]
    (DL4J layout, I == C); b: conv bias [O] or None. Semantics match
    layers.BatchNormalization.apply → ActivationLayer → ConvolutionLayer
    (ref: BatchNormalization.java eps/decay defaults, ConvolutionLayer.java)
    with the affine folded: y_hat∘γ+β == x∘(γ·inv) + (β − μ·γ·inv).
    Returns (out, new_running_mean, new_running_var) — running stats fp32,
    decay semantics `new = decay·old + (1−decay)·batch` as batch_norm().
    """
    ch_axis = 3 if (data_format == "NHWC" and x.ndim == 4) else 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    # accumulate in >= fp32 like batch_norm() (fp64 under x64 inputs)
    acc_t = jnp.promote_types(x.dtype, jnp.float32)
    # match the unfused plan's precision chain exactly (BatchNormalization
    # .apply casts params AND running stats through x.dtype before use /
    # decay — under bf16 the persistent running stats must quantize
    # identically or the two execution plans train diverging state)
    gamma32 = gamma.astype(x.dtype).astype(acc_t)
    beta32 = beta.astype(x.dtype).astype(acc_t)
    rm_q = running_mean.astype(x.dtype)
    rv_q = running_var.astype(x.dtype)
    if train:
        xf = x.astype(acc_t)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
        # same expression as batch_norm() given x.dtype running stats:
        # decay*old rounds in x.dtype BEFORE promoting into the fp32 sum
        new_mean = decay * rm_q + (1.0 - decay) * mean
        new_var = decay * rv_q + (1.0 - decay) * var
    else:
        mean, var = rm_q.astype(acc_t), rv_q.astype(acc_t)
        new_mean, new_var = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    sc = gamma32 * inv
    bb = beta32 - mean * sc

    O, I = w.shape[0], w.shape[1]
    w2 = w.reshape(O, I).T                                  # [C, K]
    bias = jnp.zeros((O,), acc_t) if b is None else b.astype(acc_t)

    if use_pallas is None:
        # DL4JTPU_FUSED_PALLAS=0 pins the XLA dot_general formulation even
        # on TPU (perf A/B of kernel vs compiler for the same fused plan)
        if os.environ.get("DL4JTPU_FUSED_PALLAS") == "0":
            use_pallas = False
        else:
            use_pallas = (jax.default_backend() == "tpu"
                          and fused_conv1x1_supported(I, O, act))

    if ch_axis == 3 or x.ndim == 2:
        shape = x.shape
        y2 = x.reshape(-1, shape[-1])
        if use_pallas:
            w2c = w2.astype(x.dtype)
            out2 = _fused_matmul_pallas(
                y2, sc.astype(jnp.float32), bb.astype(jnp.float32), w2c,
                bias.astype(jnp.float32), act,
                _pick_bm(y2.shape[0]), interpret)
        else:
            out2 = _fused_matmul_ref(y2, sc, bb, w2, bias, act)
        out = out2.reshape(shape[:-1] + (O,))
    else:
        # NCHW: keep the channel contraction as a dot_general without a
        # materialized transpose; Pallas path needs channel-minor, so
        # this layout always takes the XLA formulation
        z = (x.astype(acc_t) * sc.reshape(1, -1, 1, 1)
             + bb.reshape(1, -1, 1, 1))
        if act == "relu":
            z = jnp.maximum(z, 0.0)
        elif act != "identity":
            z = _get_act(act)(z)
        out = jnp.einsum("nchw,oc->nohw", z.astype(x.dtype),
                         w.reshape(O, I),
                         preferred_element_type=acc_t)
        out = (out + bias.reshape(1, -1, 1, 1)).astype(x.dtype)
    return out, new_mean.astype(jnp.float32), new_var.astype(jnp.float32)
