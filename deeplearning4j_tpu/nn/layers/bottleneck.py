"""Full fused ResNet bottleneck: conv1x1→BN→ReLU→conv3x3→BN→ReLU→conv1x1→
BN→(+residual)→ReLU as a chain of Pallas kernels with a recompute backward.

Why (PERF.md round-3 profile): the ResNet50 step is HBM-bound on BatchNorm
traffic — for every conv output XLA runs a separate stats-reduction pass
and a normalize pass, and the backward re-reads everything again for the
BN reductions. The round-2/3 prologue-only fusion (fused.py) measurably
LOST: it removed one normalize pass but its pallas_call boundary broke
XLA's surrounding fusions while the stats reductions stayed. This module
removes the stats passes themselves:

- every fused conv kernel emits per-channel Σout and Σout² as an EPILOGUE
  of the pass that produces the output — batch stats cost zero extra HBM
  traffic;
- the normalize+ReLU of each BN rides the NEXT conv's prologue;
- the backward is ONE pallas pass per stage: stage k's backward kernel
  computes dW_k and dz_{k-1} and, as its epilogue, the per-channel sums
  stage k-1's BN backward needs — so no separate reduction passes there
  either. All intermediates are RECOMPUTED from the saved raw conv
  outputs (which are the kernels' inputs anyway): nothing extra persists.

Kernel geometry: NHWC, grid over the batch dimension, one FULL image per
grid step resident in VMEM (ResNet50 bottleneck interiors are at most
56×56×64 ≈ 0.4 MB and weights at most 512×2048 ≈ 2 MB bf16 — far under
the ~16 MB VMEM budget), channel-sum accumulators in fp32 VMEM scratch
carried across the sequential TPU grid. The 3×3 conv is nine statically
shifted [H·W, Cin]·[Cin, Cout] matmuls over the in-VMEM zero-padded
image — MXU-shaped, no halo exchange, no dynamic shapes.

Scope: identity bottlenecks (stride 1, identity skip) AND downsample
entry blocks (stride-2 conv_a + conv shortcut with its own BN — the
ResNet50 convBlock layout); ReLU activations, NHWC, train or inference.

Backward kernels whose resident weight+fp32-dW or recompute buffers
would exceed the VMEM budget (ResNet50 stage-5 3x3 backward:
[9,512,512] w + fp32 dW ~ 14 MB; the entry-block conv-skip backwards)
run CHANNEL-SPLIT: grid (n_cb, n) with a C_in-slice of the weight, dW,
dz, recompute buffers and BN sums per step. A conv backward partitions
exactly over input channels — dW rows, dz slices, the relu' mask and
the sum epilogues are all C-local; only dy (a function of the full
K-dim gradient) is recomputed per slice, which at the affected 7x7/14x14
resolutions is noise. cb is the OUTER grid dim so each dW/sums slice
stays VMEM-resident across the whole image sweep and is written back
exactly once — no HBM accumulation revisits anywhere. With the split,
all 16 ResNet50 blocks pass the gate (fused_bottleneck_supported).

ref: the reference's fused-conv ambition lives in
deeplearning4j-cuda/.../CudnnConvolutionHelper.java:54-480 (cuDNN
conv+bias+activation fusion) and CudnnBatchNormalizationHelper.java:45-234;
this plan fuses strictly more (stats + normalize + both backward
reduction families) because on TPU the whole chain shares one memory
hierarchy.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: v1 supports the ResNet50 interior-block shapes; the gate keeps the
#: whole-image blocks + weights inside a conservative VMEM budget
_VMEM_BUDGET = 12 * 1024 * 1024


class BnParams(NamedTuple):
    gamma: jax.Array          # [C]
    beta: jax.Array           # [C]
    running_mean: jax.Array   # [C] fp32
    running_var: jax.Array    # [C] fp32


def _fwd_vmem(taps, h, w, c, k, bpe, stride=1):
    """Per-grid-step VMEM estimate for a forward conv+stats kernel:
    one image [h,w,c] + fp32 prologue buffer, fp32 accumulator + stored
    output at [h/s,w/s,k], and the full weight."""
    ho, wo = h // stride, w // stride
    if taps == 9:
        return ((h + 2) * (w + 2) * c * 4      # padded z fp32
                + h * w * c * bpe              # x image
                + h * w * k * (4 + bpe)        # acc fp32 + stored out
                + 9 * c * k * bpe)
    return (h * w * c * (4 + bpe)              # x + fp32 affine buffer
            + ho * wo * k * (4 + bpe)          # acc fp32 + stored out
            + c * k * bpe)


def _bwd_vmem(taps, h, w, c_b, k, bpe, stride=1, identity_prologue=False):
    """Per-grid-step VMEM estimate for a backward kernel holding a
    C_b-slice of the input channels. The full-K buffers (yk, g, dy) do
    not shrink with the split; everything C-indexed does. The identity
    prologue (stage-a / conv-skip backward: z_prev IS the block input)
    skips the affine/relu recompute buffers and the sums math."""
    ho, wo = h // stride, w // stride
    if taps == 9:
        return ((h + 2) * (w + 2) * (c_b + k) * 4   # z_pad slice + dy_pad
                + h * w * k * (4 + 2 * bpe)         # dy fp32 + yk + g
                + h * w * c_b * (2 * bpe + 8)       # yprev, dz, dzp/yhat f32
                + 9 * c_b * k * (bpe + 4))          # w + fp32 dW slice
    full = h * w * c_b
    recompute = 4 if identity_prologue else 12      # fp32 z-recompute bufs
    # at stride 1 dzp aliases dzs and the strided views don't exist
    strided = 0 if stride == 1 else ho * wo * c_b * 8 + full * 4
    return (full * (bpe + recompute + 4 + bpe)      # yprev, rcmp, dzs, dz
            + strided
            + ho * wo * k * (4 + 2 * bpe)           # dy fp32 + yk + g
            + c_b * k * (bpe + 4))                  # w + fp32 dW slice


def _pick_csplit(taps, h, w, c, k, bpe, stride=1, identity_prologue=False):
    """Smallest input-channel split whose per-step footprint fits the
    VMEM budget. Slices must stay lane-aligned (C_b a multiple of 128)
    — returns None when no aligned split fits (caller falls back to the
    unfused graph)."""
    split = 1
    while True:
        if _bwd_vmem(taps, h, w, c // split, k, bpe, stride,
                     identity_prologue) <= _VMEM_BUDGET:
            return split
        split *= 2
        if c % split or (c // split) % 128:
            return None


def fused_bottleneck_supported(x_shape, c_mid: int, c_out: int,
                               dtype, stride: int = 1,
                               has_skip: bool = False) -> bool:
    """VMEM gate, per-kernel: every forward pass must fit whole-image,
    and every backward stage must fit either whole-image or via an
    aligned channel split (_pick_csplit). Strided forms also require
    exact stride divisibility (the kernels subsample exactly)."""
    if len(x_shape) != 4:
        return False
    n, h, w, c_in = x_shape
    if stride > 1 and (h % stride or w % stride):
        return False          # kernels require exact stride divisibility
    if isinstance(dtype, str) and dtype in ("bf16", "bfloat16"):
        dtype = jnp.bfloat16
    bpe = jnp.dtype(dtype).itemsize
    ho, wo = h // stride, w // stride
    fwd = [_fwd_vmem(1, h, w, c_in, c_mid, bpe, stride),      # conv_a
           _fwd_vmem(9, ho, wo, c_mid, c_mid, bpe),           # conv_b
           _fwd_vmem(1, ho, wo, c_mid, c_out, bpe)]           # conv_c
    if has_skip:
        fwd.append(_fwd_vmem(1, h, w, c_in, c_out, bpe, stride))
    if max(fwd) > _VMEM_BUDGET:
        return False
    # (taps, h, w, C=yprev channels, K, stride, identity_prologue)
    bwd = [(1, ho, wo, c_mid, c_out, 1, False),               # stage c
           (9, ho, wo, c_mid, c_mid, 1, False),               # stage b
           (1, h, w, c_in, c_mid, stride, True)]              # stage a
    if has_skip:
        bwd.append((1, h, w, c_in, c_out, stride, True))      # conv skip
    return all(_pick_csplit(t, hh, ww, c, k, bpe, s, ident) is not None
               for t, hh, ww, c, k, s, ident in bwd)


# ---------------------------------------------------------------------------
# forward kernels
# ---------------------------------------------------------------------------


def _fwd1x1_kernel(x_ref, sc_ref, bb_ref, w_ref, o_ref, s1_ref, s2_ref,
                   *, act, n_img, stride=1):
    """One image: o = affine+act(x)[::stride, ::stride] @ w, with Σo / Σo²
    channel epilogue.

    x_ref [1,H,W,C]; sc/bb [1,C] fp32 (identity prologue = (1,0));
    w [C,K]; o [1,H/stride,W/stride,K]; s1/s2 [1,K] fp32 accumulated
    ACROSS the grid directly in the (constant-index, VMEM-resident)
    output blocks — no separate scratch doubles the accumulator
    footprint. stride=2 is the entry-block downsample (a strided 1x1
    conv just subsamples rows before the channel matmul).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    _, h, w_dim, c = x_ref.shape
    k = w_ref.shape[1]
    ho, wo = h // stride, w_dim // stride
    xs = x_ref[...].reshape(h, w_dim, c)
    if stride > 1:
        xs = xs[::stride, ::stride, :]
    xf = xs.reshape(ho * wo, c).astype(jnp.float32)
    z = xf * sc_ref[...] + bb_ref[...]
    if act == "relu":
        z = jnp.maximum(z, 0.0)
    out = lax.dot_general(z.astype(w_ref.dtype), w_ref[...],
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)  # [HW, K]
    o_ref[...] = out.astype(o_ref.dtype).reshape(1, ho, wo, k)
    # stats of the *stored* (dtype-rounded) output: the consumer
    # normalizes the rounded tensor, so the stats must see the same values
    of = o_ref[...].reshape(ho * wo, k).astype(jnp.float32)
    s1_ref[...] += jnp.sum(of, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(of * of, axis=0, keepdims=True)


def _fwd3x3_kernel(x_ref, sc_ref, bb_ref, w_ref, o_ref, s1_ref, s2_ref,
                   *, act, n_img):
    """One image: 3x3 same-pad conv of affine+act(x), stats epilogue.

    w_ref [9, C, K] (tap-major: dy*3+dx); the conv is nine shifted
    matmuls over the in-VMEM zero-padded image.
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    _, h, w_dim, c = x_ref.shape
    k = w_ref.shape[2]
    xf = x_ref[...].reshape(h, w_dim, c).astype(jnp.float32)
    z = xf * sc_ref[...][0][None, None, :] + bb_ref[...][0][None, None, :]
    if act == "relu":
        z = jnp.maximum(z, 0.0)
    zp = jnp.pad(z, ((1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((h * w_dim, k), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = zp[dy:dy + h, dx:dx + w_dim, :].reshape(h * w_dim, c)
            acc += lax.dot_general(
                xs.astype(w_ref.dtype), w_ref[dy * 3 + dx],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[...] = acc.astype(o_ref.dtype).reshape(1, h, w_dim, k)
    of = o_ref[...].reshape(h * w_dim, k).astype(jnp.float32)
    s1_ref[...] += jnp.sum(of, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(of * of, axis=0, keepdims=True)


def _img_spec(h, w, c):
    return pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0))


def _bcast_spec(r, c):
    return pl.BlockSpec((r, c), lambda i: (0, 0))


def _bcast_spec3(a, b, c):
    return pl.BlockSpec((a, b, c), lambda i: (0, 0, 0))


def _fwd_conv_stats(x, sc, bb, w, *, taps: int, act: str,
                    interpret: bool, stride: int = 1):
    """Dispatch one fused conv+stats pass. x [N,H,W,C]; w [C,K] (1x1) or
    [9,C,K] (3x3, stride-1 only). Returns (out [N,H/s,W/s,K], s1 [K],
    s2 [K])."""
    n, h, wd, c = x.shape
    k = w.shape[-1]
    if taps == 1:
        kern = functools.partial(_fwd1x1_kernel, stride=stride)
        w_spec = _bcast_spec(c, k)
    else:
        assert stride == 1, "3x3 stage is stride-1 in ResNet bottlenecks"
        kern = _fwd3x3_kernel
        w_spec = _bcast_spec3(9, c, k)
    ho, wo = h // stride, wd // stride
    out, s1, s2 = pl.pallas_call(
        functools.partial(kern, act=act, n_img=n),
        grid=(n,),
        in_specs=[_img_spec(h, wd, c), _bcast_spec(1, c), _bcast_spec(1, c),
                  w_spec],
        out_specs=[_img_spec(ho, wo, k), _bcast_spec(1, k),
                   _bcast_spec(1, k)],
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, k), x.dtype),
                   jax.ShapeDtypeStruct((1, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32)],
        interpret=interpret,
    )(x, sc[None, :], bb[None, :], w)
    return out, s1[0], s2[0]


# ---------------------------------------------------------------------------
# backward kernels — one pass per stage
# ---------------------------------------------------------------------------
#
# Stage k (output y_k = conv_k(z_{k-1}), z_k = relu(sc_k∘y_k + bb_k)):
# given dz0_k = (∂L/∂z_k)∘relu'(·) and stage-k BN-backward sums
# (m1 = mean(dz0_k), m2 = mean(dz0_k∘ŷ_k) over the batch), the gradient
# w.r.t. the raw conv output is the standard training-BN backward
#     dy_k = sc_k ∘ (dz0_k − m1 − ŷ_k∘m2)        ŷ_k = (y_k − μ)·inv
# The kernel then computes in the same pass
#     dW_k  += z_{k-1}ᵀ @ dy_k           (recomputing z_{k-1} from y_{k-1})
#     dz0_{k-1} = (dy_k @ W_kᵀ) ∘ relu'(z0_{k-1})
# and EMITS the next stage's sums Σdz0_{k-1}, Σdz0_{k-1}∘ŷ_{k-1} as its
# epilogue, so stage k-1 starts with its reductions already done.


def _bwd1x1_kernel(yk_ref, g_ref, yprev_ref, w_ref,
                   aff_k_ref, aff_p_ref,
                   dz_ref, dw_ref, sums_ref,
                   *, act_prev, n_img, gmode, stride=1, img_axis=0):
    """One image (or one image × C-slice) of stage-k backward (k a 1x1
    conv).

    yk_ref    [1,H,W,K]  raw conv_k output (for ŷ_k / relu' recompute)
    g_ref     [1,H,W,K]  dz0_k when gmode=='dz0' (already relu-masked),
                         or dy_k directly when gmode=='dy'
    yprev_ref [1,H,W,C]  raw stage k-1 output (recompute z_{k-1})
    w_ref     [C,K]      conv_k weight
    aff_k_ref [6,K] fp32 rows: sc_k, bb_k(unused), inv_k, mu_k, m1, m2
    aff_p_ref [4,C] fp32 rows: sc_{k-1}, bb_{k-1}, inv_{k-1}, mu_{k-1}
    dz_ref    [1,H,W,C]  OUT: dz0_{k-1}
    dw_ref    [C,K]      OUT: dW_k
    sums_ref  [2,C] fp32 OUT: Σdz0_{k-1}, Σdz0_{k-1}∘ŷ_{k-1}

    Under a channel split every C-dim ref carries a C_b slice and the
    grid is (n_cb, n) with img_axis=1: the math is identical because a
    1x1 conv backward is C-local (dz columns, dw rows, the mask and the
    sums all partition; only dy spans K and is recomputed per slice).

    act_prev == "identity" asserts the FULL identity prologue (stage-a /
    conv-skip backward: z_{k-1} IS the block input, affine rows are
    (1,0) by construction) — the kernel then skips the affine/mask
    recompute and leaves the (caller-discarded) sums at zero.
    """
    i = pl.program_id(img_axis)
    identity = act_prev == "identity"

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        sums_ref[...] = jnp.zeros_like(sums_ref)

    _, h, wd, c = yprev_ref.shape
    k = yk_ref.shape[3]
    ho, wo = h // stride, wd // stride
    hw_o = ho * wo
    g = g_ref[...].reshape(hw_o, k).astype(jnp.float32)
    if gmode == "dz0":
        yk = yk_ref[...].reshape(hw_o, k).astype(jnp.float32)
        sck = aff_k_ref[0, :][None, :]
        invk = aff_k_ref[2, :][None, :]
        muk = aff_k_ref[3, :][None, :]
        m1 = aff_k_ref[4, :][None, :]
        m2 = aff_k_ref[5, :][None, :]
        yhat = (yk - muk) * invk
        dy = sck * (g - m1 - yhat * m2)                     # [HWo, K]
    else:
        dy = g
    # recompute z_{k-1} (full resolution; the conv consumed the
    # ::stride subsample)
    yp3 = yprev_ref[...].reshape(h, wd, c).astype(jnp.float32)
    if identity:
        z0p3 = zp3 = yp3
    else:
        scp = aff_p_ref[0, :][None, None, :]
        bbp = aff_p_ref[1, :][None, None, :]
        z0p3 = yp3 * scp + bbp
        zp3 = jnp.maximum(z0p3, 0.0) if act_prev == "relu" else z0p3
    if stride > 1:
        zp_s = zp3[::stride, ::stride, :].reshape(hw_o, c)
    else:
        zp_s = zp3.reshape(hw_o, c)
    dw_ref[...] += lax.dot_general(
        zp_s.astype(yk_ref.dtype), dy.astype(yk_ref.dtype),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dzs = lax.dot_general(dy.astype(w_ref.dtype), w_ref[...],
                          (((1,), (1,)), ((), ())),
                          preferred_element_type=jnp.float32)  # [HWo, C]
    if act_prev == "relu":
        z0_s = (z0p3[::stride, ::stride, :].reshape(hw_o, c)
                if stride > 1 else z0p3.reshape(hw_o, c))
        dzs = jnp.where(z0_s > 0, dzs, 0.0)
    if stride > 1:
        # interleave back to full resolution (gradient is zero at the
        # positions the strided conv never read): pad+reshape, no scatter
        dz3 = dzs.reshape(ho, 1, wo, 1, c)
        dz3 = jnp.pad(dz3, ((0, 0), (0, stride - 1), (0, 0),
                            (0, stride - 1), (0, 0)))
        dzp = dz3.reshape(h, wd, c).reshape(h * wd, c)
    else:
        dzp = dzs
    dz_ref[...] = dzp.astype(dz_ref.dtype).reshape(1, h, wd, c)
    if identity:
        return    # sums are only consumed by a real BN prologue
    invp = aff_p_ref[2, :][None, :]
    mup = aff_p_ref[3, :][None, :]
    # sums over the full-res dz (zero at unread positions, so summing
    # the strided values with strided yhat is exact)
    if stride > 1:
        yhat_s = (yp3[::stride, ::stride, :].reshape(hw_o, c) - mup) * invp
        sums_ref[0:1, :] += jnp.sum(dzs, axis=0, keepdims=True)
        sums_ref[1:2, :] += jnp.sum(dzs * yhat_s, axis=0, keepdims=True)
    else:
        yhat_p = (yp3.reshape(h * wd, c) - mup) * invp
        sums_ref[0:1, :] += jnp.sum(dzp, axis=0, keepdims=True)
        sums_ref[1:2, :] += jnp.sum(dzp * yhat_p, axis=0, keepdims=True)


def _bwd3x3_kernel(yk_ref, g_ref, yprev_ref, w_ref,
                   aff_k_ref, aff_p_ref,
                   dz_ref, dw_ref, sums_ref,
                   *, act_prev, n_img, gmode, img_axis=0):
    """3x3 twin of _bwd1x1_kernel: w_ref [9,C,K];
    dW via nine shifted-input matmuls, dz_{k-1} via the transposed taps
    (full-correlation with the flipped kernel). Channel-split form as in
    _bwd1x1_kernel (the zero-padding, tap shifts and mask are C-local;
    the 3x3 stage always has a real BN prologue, so no identity path)."""
    i = pl.program_id(img_axis)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        sums_ref[...] = jnp.zeros_like(sums_ref)

    _, h, wd, c = yprev_ref.shape
    k = yk_ref.shape[3]
    hw = h * wd
    g = g_ref[...].reshape(hw, k).astype(jnp.float32)
    if gmode == "dz0":
        yk = yk_ref[...].reshape(hw, k).astype(jnp.float32)
        sck = aff_k_ref[0, :][None, :]
        invk = aff_k_ref[2, :][None, :]
        muk = aff_k_ref[3, :][None, :]
        m1 = aff_k_ref[4, :][None, :]
        m2 = aff_k_ref[5, :][None, :]
        yhat = (yk - muk) * invk
        dy = sck * (g - m1 - yhat * m2)
    else:
        dy = g
    yp = yprev_ref[...].reshape(h, wd, c).astype(jnp.float32)
    scp = aff_p_ref[0, :][None, None, :]
    bbp = aff_p_ref[1, :][None, None, :]
    z0p = yp * scp + bbp
    zp = jnp.maximum(z0p, 0.0) if act_prev == "relu" else z0p
    zp_pad = jnp.pad(zp, ((1, 1), (1, 1), (0, 0)))
    dy3 = dy.reshape(h, wd, k)
    dy_pad = jnp.pad(dy3, ((1, 1), (1, 1), (0, 0)))
    dzp = jnp.zeros((hw, c), jnp.float32)
    for t in range(9):
        dyy, dxx = divmod(t, 3)
        # dW tap t sums z_{k-1}[shifted] · dy
        xs = zp_pad[dyy:dyy + h, dxx:dxx + wd, :].reshape(hw, c)
        dw_ref[t, :, :] += lax.dot_general(
            xs.astype(yk_ref.dtype), dy.astype(yk_ref.dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        # dz tap: correlation with the mirrored offset (2-dy, 2-dx)
        gs = dy_pad[2 - dyy:2 - dyy + h,
                    2 - dxx:2 - dxx + wd, :].reshape(hw, k)
        dzp += lax.dot_general(gs.astype(w_ref.dtype), w_ref[t],
                               (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    z0f = z0p.reshape(hw, c)
    if act_prev == "relu":
        dzp = jnp.where(z0f > 0, dzp, 0.0)
    dz_ref[...] = dzp.astype(dz_ref.dtype).reshape(1, h, wd, c)
    invp = aff_p_ref[2, :][None, :]
    mup = aff_p_ref[3, :][None, :]
    yhat_p = (yp.reshape(hw, c) - mup) * invp
    sums_ref[0:1, :] += jnp.sum(dzp, axis=0, keepdims=True)
    sums_ref[1:2, :] += jnp.sum(dzp * yhat_p, axis=0, keepdims=True)


def _bwd_stage(yk, g, yprev, w, aff_k, aff_p, *, taps, act_prev, gmode,
               interpret, stride: int = 1):
    """One backward stage pass. Returns (dz0_prev [N,H,W,C] full-res, dW,
    sums [2,C] = (Σdz0_prev, Σdz0_prev∘ŷ_prev)).

    Picks the channel split from the same VMEM model as the support
    gate: split == 1 is the whole-image kernel on grid (n,); split > 1
    runs grid (split, n) — cb OUTER, so each dW/sums slice is resident
    across the image sweep and written back once. The two forms are
    arithmetically identical (same fp32 accumulation order per slice)."""
    n, h, wd, c = yprev.shape
    k = yk.shape[3]
    ho, wo = h // stride, wd // stride
    bpe = jnp.dtype(yprev.dtype).itemsize
    split = _pick_csplit(taps, h, wd, c, k, bpe, stride,
                         act_prev == "identity")
    if split is None:
        raise ValueError(
            f"no aligned channel split fits VMEM for backward stage "
            f"taps={taps} h={h} w={wd} c={c} k={k} stride={stride} — "
            "fused_bottleneck_supported should have rejected this block")
    dw_shape = (c, k) if taps == 1 else (9, c, k)
    if split == 1:
        if taps == 1:
            kern = functools.partial(_bwd1x1_kernel, stride=stride)
            w_spec = _bcast_spec(c, k)
            dw_spec = _bcast_spec(c, k)
        else:
            assert stride == 1
            kern = _bwd3x3_kernel
            w_spec = _bcast_spec3(9, c, k)
            dw_spec = _bcast_spec3(9, c, k)
        grid = (n,)
        in_specs = [_img_spec(ho, wo, k), _img_spec(ho, wo, k),
                    _img_spec(h, wd, c), w_spec,
                    _bcast_spec(6, k), _bcast_spec(4, c)]
        out_specs = [_img_spec(h, wd, c), dw_spec, _bcast_spec(2, c)]
    else:
        c_b = c // split
        if taps == 1:
            kern = functools.partial(_bwd1x1_kernel, stride=stride,
                                     img_axis=1)
            w_spec = pl.BlockSpec((c_b, k), lambda cb, i: (cb, 0))
            dw_spec = pl.BlockSpec((c_b, k), lambda cb, i: (cb, 0))
        else:
            assert stride == 1
            kern = functools.partial(_bwd3x3_kernel, img_axis=1)
            w_spec = pl.BlockSpec((9, c_b, k), lambda cb, i: (0, cb, 0))
            dw_spec = pl.BlockSpec((9, c_b, k), lambda cb, i: (0, cb, 0))
        grid = (split, n)
        in_specs = [
            pl.BlockSpec((1, ho, wo, k), lambda cb, i: (i, 0, 0, 0)),
            pl.BlockSpec((1, ho, wo, k), lambda cb, i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, wd, c_b), lambda cb, i: (i, 0, 0, cb)),
            w_spec,
            pl.BlockSpec((6, k), lambda cb, i: (0, 0)),
            pl.BlockSpec((4, c_b), lambda cb, i: (0, cb)),
        ]
        out_specs = [
            pl.BlockSpec((1, h, wd, c_b), lambda cb, i: (i, 0, 0, cb)),
            dw_spec,
            pl.BlockSpec((2, c_b), lambda cb, i: (0, cb)),
        ]
    dz, dw, sums = pl.pallas_call(
        functools.partial(kern, act_prev=act_prev, n_img=n, gmode=gmode),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[jax.ShapeDtypeStruct((n, h, wd, c), yprev.dtype),
                   jax.ShapeDtypeStruct(dw_shape, jnp.float32),
                   jax.ShapeDtypeStruct((2, c), jnp.float32)],
        interpret=interpret,
    )(yk, g, yprev, w, aff_k, aff_p)
    return dz, dw, sums


# ---------------------------------------------------------------------------
# the bottleneck orchestration (custom_vjp)
# ---------------------------------------------------------------------------


def _finalize_stats(s1, s2, count):
    mean = s1 / count
    var = jnp.maximum(s2 / count - mean * mean, 0.0)
    return mean, var


def _affine(gamma, beta, mean, var, eps):
    inv = lax.rsqrt(var + eps)
    sc = gamma * inv
    bb = beta - mean * sc
    return sc, bb, inv


def _aff_rows_k(sc, bb, inv, mu, m1, m2):
    return jnp.stack([sc, bb, inv, mu, m1, m2]).astype(jnp.float32)


def _aff_rows_p(sc, bb, inv, mu):
    return jnp.stack([sc, bb, inv, mu]).astype(jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bottleneck_core(cfg, x, wa, wb, wc, ga, be_a, gb, be_b, gc, be_c):
    """Returns (out, batch_stats6). cfg = (eps, interpret). The
    batch-stat outputs are NON-differentiable byproducts: their
    cotangents are ignored in the vjp — they only feed running-average
    state, which no loss differentiates through (same contract as
    fused.py keeping stats outside its vjp)."""
    out, res = _bottleneck_fwd_impl(cfg, x, wa, wb, wc, ga, be_a, gb,
                                    be_b, gc, be_c)
    return out, res[4]


def _bottleneck_fwd_impl(cfg, x, wa, wb, wc, ga, be_a, gb, be_b, gc,
                         be_c):
    eps, interpret = cfg
    n, h, wd, _ = x.shape
    count = n * h * wd
    ones_c = jnp.ones((x.shape[3],), jnp.float32)
    zeros_c = jnp.zeros((x.shape[3],), jnp.float32)
    # stage a: identity prologue (x is the block input, already activated)
    ya, s1a, s2a = _fwd_conv_stats(x, ones_c, zeros_c, wa, taps=1,
                                   act="identity", interpret=interpret)
    mua, vara = _finalize_stats(s1a, s2a, count)
    sca, bba, inva = _affine(ga, be_a, mua, vara, eps)
    # stage b: 3x3
    yb, s1b, s2b = _fwd_conv_stats(ya, sca, bba, wb, taps=9, act="relu",
                                   interpret=interpret)
    mub, varb = _finalize_stats(s1b, s2b, count)
    scb, bbb, invb = _affine(gb, be_b, mub, varb, eps)
    # stage c: 1x1
    yc, s1c, s2c = _fwd_conv_stats(yb, scb, bbb, wc, taps=1, act="relu",
                                   interpret=interpret)
    muc, varc = _finalize_stats(s1c, s2c, count)
    scc, bbc, invc = _affine(gc, be_c, muc, varc, eps)
    # tail: norm_c + residual + relu (pure elementwise — XLA fuses)
    pre = yc.astype(jnp.float32) * scc + bbc + x.astype(jnp.float32)
    out = jnp.maximum(pre, 0.0).astype(x.dtype)
    stats = (mua, vara, mub, varb, muc, varc)
    # residuals: raw conv outputs only — `pre` is recomputed in the
    # backward from yc and x (saving it would persist a full fp32
    # activation tensor per block, against the module's design)
    return out, (x, ya, yb, yc, stats)


def _bottleneck_vjp_fwd(cfg, x, wa, wb, wc, ga, be_a, gb, be_b, gc,
                        be_c):
    out, res = _bottleneck_fwd_impl(cfg, x, wa, wb, wc, ga, be_a, gb,
                                    be_b, gc, be_c)
    return (out, res[4]), \
        res + ((wa, wb, wc, ga, gb, gc, be_a, be_b, be_c),)


def _bottleneck_vjp_bwd(cfg, res, cts):
    eps, interpret = cfg
    g, _stat_cts = cts     # stats feed running averages only: cotangents
    #                        ignored by contract (see _bottleneck_core)
    x, ya, yb, yc, stats, weights = res
    wa, wb, wc, ga, gb, gc, be_a, be_b, be_c = weights
    mua, vara, mub, varb, muc, varc = stats
    n, h, wd, _ = x.shape
    count = n * h * wd
    sca, bba, inva = _affine(ga, be_a, mua, vara, eps)
    scb, bbb, invb = _affine(gb, be_b, mub, varb, eps)
    scc, bbc, invc = _affine(gc, be_c, muc, varc, eps)

    # tail backward (elementwise + 2 channel reductions; XLA fuses):
    # dz_c0 = g∘relu'(pre); the same tensor is the skip gradient.
    # pre recomputed from the saved raw tensors (elementwise, fuses)
    pre = yc.astype(jnp.float32) * scc + bbc + x.astype(jnp.float32)
    gz = jnp.where(pre > 0, g.astype(jnp.float32), 0.0)   # [N,H,W,K3]
    dx_skip = gz
    ycf = yc.astype(jnp.float32)
    yhat_c = (ycf - muc) * invc
    m1c = jnp.mean(gz, axis=(0, 1, 2))
    m2c = jnp.mean(gz * yhat_c, axis=(0, 1, 2))
    dgc = jnp.sum(gz * yhat_c, axis=(0, 1, 2))
    dbc = jnp.sum(gz, axis=(0, 1, 2))

    # stage c backward (one pass): consumes dz0_c (gz), recomputes z_b,
    # emits dW_c, dz0_b and stage-b sums
    aff_c = _aff_rows_k(scc, bbc, invc, muc, m1c, m2c)
    aff_b = _aff_rows_p(scb, bbb, invb, mub)
    dz0b, dwc, sums_b = _bwd_stage(yc, gz.astype(yc.dtype), yb, wc,
                                   aff_c, aff_b, taps=1, act_prev="relu",
                                   gmode="dz0", interpret=interpret)
    m1b = sums_b[0] / count
    m2b = sums_b[1] / count
    dgb = sums_b[1]
    dbb_ = sums_b[0]

    # stage b backward (3x3)
    aff_bk = _aff_rows_k(scb, bbb, invb, mub, m1b, m2b)
    aff_a = _aff_rows_p(sca, bba, inva, mua)
    dz0a, dwb, sums_a = _bwd_stage(yb, dz0b, ya, wb, aff_bk, aff_a,
                                   taps=9, act_prev="relu", gmode="dz0",
                                   interpret=interpret)
    m1a = sums_a[0] / count
    m2a = sums_a[1] / count
    dga = sums_a[1]
    dba = sums_a[0]

    # stage a backward: prologue was identity (z_prev = x), so act_prev
    # is identity and the emitted sums are unused
    aff_ak = _aff_rows_k(sca, bba, inva, mua, m1a, m2a)
    c_in = x.shape[3]
    aff_x = _aff_rows_p(jnp.ones((c_in,)), jnp.zeros((c_in,)),
                        jnp.ones((c_in,)), jnp.zeros((c_in,)))
    dx_main, dwa, _ = _bwd_stage(ya, dz0a, x, wa, aff_ak, aff_x, taps=1,
                                 act_prev="identity", gmode="dz0",
                                 interpret=interpret)
    dx = (dx_main.astype(jnp.float32) + dx_skip).astype(x.dtype)
    return (dx, dwa.astype(wa.dtype), dwb.astype(wb.dtype),
            dwc.astype(wc.dtype), dga.astype(ga.dtype),
            dba.astype(be_a.dtype), dgb.astype(gb.dtype),
            dbb_.astype(be_b.dtype), dgc.astype(gc.dtype),
            dbc.astype(be_c.dtype))


_bottleneck_core.defvjp(_bottleneck_vjp_fwd, _bottleneck_vjp_bwd)


# ---------------------------------------------------------------------------
# downsample (entry) blocks: conv skip + stride
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bottleneck_ds_core(cfg, x, wa, wb, wc, ws, ga, be_a, gb, be_b, gc,
                        be_c, gs, be_s):
    """Downsample bottleneck: stride on conv_a and on the conv shortcut
    (ws + its own BN). cfg = (eps, stride, interpret). Returns
    (out, batch_stats8); stat cotangents ignored as in
    _bottleneck_core."""
    out, res = _bottleneck_ds_fwd_impl(cfg, x, wa, wb, wc, ws, ga, be_a,
                                       gb, be_b, gc, be_c, gs, be_s)
    return out, res[5]


def _bottleneck_ds_fwd_impl(cfg, x, wa, wb, wc, ws, ga, be_a, gb, be_b,
                            gc, be_c, gs, be_s):
    eps, stride, interpret = cfg
    n, h, wd, _ = x.shape
    ho, wo = h // stride, wd // stride
    count = n * ho * wo
    ones_c = jnp.ones((x.shape[3],), jnp.float32)
    zeros_c = jnp.zeros((x.shape[3],), jnp.float32)
    ya, s1a, s2a = _fwd_conv_stats(x, ones_c, zeros_c, wa, taps=1,
                                   act="identity", interpret=interpret,
                                   stride=stride)
    mua, vara = _finalize_stats(s1a, s2a, count)
    sca, bba, inva = _affine(ga, be_a, mua, vara, eps)
    yb, s1b, s2b = _fwd_conv_stats(ya, sca, bba, wb, taps=9, act="relu",
                                   interpret=interpret)
    mub, varb = _finalize_stats(s1b, s2b, count)
    scb, bbb, invb = _affine(gb, be_b, mub, varb, eps)
    yc, s1c, s2c = _fwd_conv_stats(yb, scb, bbb, wc, taps=1, act="relu",
                                   interpret=interpret)
    muc, varc = _finalize_stats(s1c, s2c, count)
    scc, bbc, invc = _affine(gc, be_c, muc, varc, eps)
    # conv shortcut: same input, own stride + BN
    ys, s1s, s2s = _fwd_conv_stats(x, ones_c, zeros_c, ws, taps=1,
                                   act="identity", interpret=interpret,
                                   stride=stride)
    mus, vars_ = _finalize_stats(s1s, s2s, count)
    scs, bbs, invs = _affine(gs, be_s, mus, vars_, eps)
    pre = (yc.astype(jnp.float32) * scc + bbc
           + ys.astype(jnp.float32) * scs + bbs)
    out = jnp.maximum(pre, 0.0).astype(x.dtype)
    stats = (mua, vara, mub, varb, muc, varc, mus, vars_)
    return out, (x, ya, yb, yc, ys, stats)


def _bottleneck_ds_vjp_fwd(cfg, x, wa, wb, wc, ws, ga, be_a, gb, be_b,
                           gc, be_c, gs, be_s):
    out, res = _bottleneck_ds_fwd_impl(cfg, x, wa, wb, wc, ws, ga, be_a,
                                       gb, be_b, gc, be_c, gs, be_s)
    return (out, res[5]), \
        res + ((wa, wb, wc, ws, ga, gb, gc, gs, be_a, be_b, be_c, be_s),)


def _bottleneck_ds_vjp_bwd(cfg, res, cts):
    eps, stride, interpret = cfg
    g, _stat_cts = cts
    x, ya, yb, yc, ys, stats, weights = res
    wa, wb, wc, ws, ga, gb, gc, gs, be_a, be_b, be_c, be_s = weights
    mua, vara, mub, varb, muc, varc, mus, vars_ = stats
    n, h, wd, _ = x.shape
    ho, wo = h // stride, wd // stride
    count = n * ho * wo
    sca, bba, inva = _affine(ga, be_a, mua, vara, eps)
    scb, bbb, invb = _affine(gb, be_b, mub, varb, eps)
    scc, bbc, invc = _affine(gc, be_c, muc, varc, eps)
    scs, bbs, invs = _affine(gs, be_s, mus, vars_, eps)

    pre = (yc.astype(jnp.float32) * scc + bbc
           + ys.astype(jnp.float32) * scs + bbs)
    gz = jnp.where(pre > 0, g.astype(jnp.float32), 0.0)
    ycf = yc.astype(jnp.float32)
    yhat_c = (ycf - muc) * invc
    m1c = jnp.mean(gz, axis=(0, 1, 2))
    m2c = jnp.mean(gz * yhat_c, axis=(0, 1, 2))
    dgc = jnp.sum(gz * yhat_c, axis=(0, 1, 2))
    dbc = jnp.sum(gz, axis=(0, 1, 2))
    ysf = ys.astype(jnp.float32)
    yhat_s = (ysf - mus) * invs
    m1s = jnp.mean(gz, axis=(0, 1, 2))
    m2s = jnp.mean(gz * yhat_s, axis=(0, 1, 2))
    dgs = jnp.sum(gz * yhat_s, axis=(0, 1, 2))
    dbs = jnp.sum(gz, axis=(0, 1, 2))

    gzt = gz.astype(yc.dtype)
    aff_c = _aff_rows_k(scc, bbc, invc, muc, m1c, m2c)
    aff_b = _aff_rows_p(scb, bbb, invb, mub)
    dz0b, dwc, sums_b = _bwd_stage(yc, gzt, yb, wc, aff_c, aff_b, taps=1,
                                   act_prev="relu", gmode="dz0",
                                   interpret=interpret)
    m1b = sums_b[0] / count
    m2b = sums_b[1] / count
    dgb = sums_b[1]
    dbb_ = sums_b[0]

    aff_bk = _aff_rows_k(scb, bbb, invb, mub, m1b, m2b)
    aff_a = _aff_rows_p(sca, bba, inva, mua)
    dz0a, dwb, sums_a = _bwd_stage(yb, dz0b, ya, wb, aff_bk, aff_a,
                                   taps=9, act_prev="relu", gmode="dz0",
                                   interpret=interpret)
    m1a = sums_a[0] / count
    m2a = sums_a[1] / count
    dga = sums_a[1]
    dba = sums_a[0]

    c_in = x.shape[3]
    aff_id = _aff_rows_p(jnp.ones((c_in,)), jnp.zeros((c_in,)),
                         jnp.ones((c_in,)), jnp.zeros((c_in,)))
    aff_ak = _aff_rows_k(sca, bba, inva, mua, m1a, m2a)
    dx_main, dwa, _ = _bwd_stage(ya, dz0a, x, wa, aff_ak, aff_id, taps=1,
                                 act_prev="identity", gmode="dz0",
                                 interpret=interpret, stride=stride)
    aff_sk = _aff_rows_k(scs, bbs, invs, mus, m1s, m2s)
    dx_skip, dws, _ = _bwd_stage(ys, gzt, x, ws, aff_sk, aff_id, taps=1,
                                 act_prev="identity", gmode="dz0",
                                 interpret=interpret, stride=stride)
    dx = (dx_main.astype(jnp.float32)
          + dx_skip.astype(jnp.float32)).astype(x.dtype)
    return (dx, dwa.astype(wa.dtype), dwb.astype(wb.dtype),
            dwc.astype(wc.dtype), dws.astype(ws.dtype),
            dga.astype(ga.dtype), dba.astype(be_a.dtype),
            dgb.astype(gb.dtype), dbb_.astype(be_b.dtype),
            dgc.astype(gc.dtype), dbc.astype(be_c.dtype),
            dgs.astype(gs.dtype), dbs.astype(be_s.dtype))


_bottleneck_ds_core.defvjp(_bottleneck_ds_vjp_fwd, _bottleneck_ds_vjp_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


def fused_bottleneck(
    x: jax.Array,
    wa: jax.Array, bn_a: BnParams,
    wb: jax.Array, bn_b: BnParams,
    wc: jax.Array, bn_c: BnParams,
    *,
    train: bool,
    w_skip: jax.Array = None, bn_skip: BnParams = None,
    stride: int = 1,
    eps: float = 1e-5,
    decay: float = 0.9,
    interpret: bool = False,
) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
    """ResNet bottleneck, fully fused.

    x [N,H,W,Cin] NHWC (already post-ReLU block input); wa [Cin,Cmid],
    wb [9,Cmid,Cmid] (tap-major 3x3), wc [Cmid,Cout].

    Identity form (w_skip=None, stride=1, Cout == Cin): out =
    relu(norm_c(conv_c(...)) + x). Downsample (entry) form: w_skip
    [Cin,Cout] + bn_skip give the conv shortcut, and `stride` applies to
    conv_a AND the shortcut (the ResNet50 layout) — out =
    relu(norm_c(...) + norm_s(conv_s(x))).

    Returns (out, new_running_stats): 6 entries (mean/var for a,b,c) or
    8 (+ skip) fp32, decayed like layers.BatchNormalization
    (`new = decay·old + (1−decay)·batch`, rounding decay·old through
    x.dtype exactly like the unfused plan).

    Inference (train=False) uses running stats — the chain is then pure
    elementwise+matmul with no stats dependency.
    """
    ds = w_skip is not None
    if ds != (bn_skip is not None):
        raise ValueError("w_skip and bn_skip go together")
    if stride != 1 and not ds:
        raise ValueError("stride != 1 requires the conv shortcut")

    def _decayed(pairs):
        # decay*old ROUNDS through x.dtype exactly like the unfused
        # BatchNormalization (fused.py precision-chain note): under bf16
        # the persistent running stats would otherwise drift apart
        # between the two execution plans
        return tuple(
            (decay * old.astype(x.dtype) + (1.0 - decay) * new)
            .astype(jnp.float32) for old, new in pairs)

    if train:
        if ds:
            cfg = (eps, stride, interpret)
            out, bs = _bottleneck_ds_core(
                cfg, x, wa, wb, wc, w_skip, bn_a.gamma, bn_a.beta,
                bn_b.gamma, bn_b.beta, bn_c.gamma, bn_c.beta,
                bn_skip.gamma, bn_skip.beta)
            mua, vara, mub, varb, muc, varc, mus, vars_ = bs
            return out, _decayed((
                (bn_a.running_mean, mua), (bn_a.running_var, vara),
                (bn_b.running_mean, mub), (bn_b.running_var, varb),
                (bn_c.running_mean, muc), (bn_c.running_var, varc),
                (bn_skip.running_mean, mus),
                (bn_skip.running_var, vars_)))
        cfg = (eps, interpret)
        out, batch_stats = _bottleneck_core(
            cfg, x, wa, wb, wc, bn_a.gamma, bn_a.beta, bn_b.gamma,
            bn_b.beta, bn_c.gamma, bn_c.beta)
        mua, vara, mub, varb, muc, varc = batch_stats
        return out, _decayed((
            (bn_a.running_mean, mua), (bn_a.running_var, vara),
            (bn_b.running_mean, mub), (bn_b.running_var, varb),
            (bn_c.running_mean, muc), (bn_c.running_var, varc)))
    # inference: running-stat affines, no stats needed
    sca, bba, _ = _affine(bn_a.gamma.astype(jnp.float32),
                          bn_a.beta.astype(jnp.float32),
                          bn_a.running_mean, bn_a.running_var, eps)
    scb, bbb, _ = _affine(bn_b.gamma.astype(jnp.float32),
                          bn_b.beta.astype(jnp.float32),
                          bn_b.running_mean, bn_b.running_var, eps)
    scc, bbc, _ = _affine(bn_c.gamma.astype(jnp.float32),
                          bn_c.beta.astype(jnp.float32),
                          bn_c.running_mean, bn_c.running_var, eps)
    ones_c = jnp.ones((x.shape[3],), jnp.float32)
    zeros_c = jnp.zeros((x.shape[3],), jnp.float32)
    ya, _, _ = _fwd_conv_stats(x, ones_c, zeros_c, wa, taps=1,
                               act="identity", interpret=interpret,
                               stride=stride)
    yb, _, _ = _fwd_conv_stats(ya, sca, bba, wb, taps=9, act="relu",
                               interpret=interpret)
    yc, _, _ = _fwd_conv_stats(yb, scb, bbb, wc, taps=1, act="relu",
                               interpret=interpret)
    if ds:
        scs, bbs, _ = _affine(bn_skip.gamma.astype(jnp.float32),
                              bn_skip.beta.astype(jnp.float32),
                              bn_skip.running_mean, bn_skip.running_var,
                              eps)
        ys, _, _ = _fwd_conv_stats(x, ones_c, zeros_c, w_skip, taps=1,
                                   act="identity", interpret=interpret,
                                   stride=stride)
        shortcut = ys.astype(jnp.float32) * scs + bbs
    else:
        shortcut = x.astype(jnp.float32)
    pre = yc.astype(jnp.float32) * scc + bbc + shortcut
    out = jnp.maximum(pre, 0.0).astype(x.dtype)
    stats = (bn_a.running_mean, bn_a.running_var, bn_b.running_mean,
             bn_b.running_var, bn_c.running_mean, bn_c.running_var)
    if ds:
        stats = stats + (bn_skip.running_mean, bn_skip.running_var)
    return out, stats


def reference_bottleneck(x, wa, bn_a, wb, bn_b, wc, bn_c, *, train,
                         w_skip=None, bn_skip=None, stride=1,
                         eps=1e-5, decay=0.9):
    """Unfused jnp composition with IDENTICAL semantics — the equivalence
    oracle for the kernel chain (autodiff supplies its backward)."""
    def conv1x1(z, w, s=1):
        if s > 1:
            z = z[:, ::s, ::s, :]
        return jnp.einsum("nhwc,ck->nhwk", z, w,
                          preferred_element_type=jnp.float32)

    def conv3x3(z, w9):
        zp = jnp.pad(z, ((0, 0), (1, 1), (1, 1), (0, 0)))
        acc = 0
        for t in range(9):
            dy, dx = divmod(t, 3)
            acc = acc + jnp.einsum(
                "nhwc,ck->nhwk",
                zp[:, dy:dy + z.shape[1], dx:dx + z.shape[2], :], w9[t],
                preferred_element_type=jnp.float32)
        return acc

    def bn(y, p, train):
        yf = y.astype(jnp.float32)
        if train:
            mean = jnp.mean(yf, axis=(0, 1, 2))
            var = jnp.maximum(
                jnp.mean(yf * yf, axis=(0, 1, 2)) - mean * mean, 0.0)
        else:
            mean, var = p.running_mean, p.running_var
        inv = lax.rsqrt(var + eps)
        out = (yf - mean) * inv * p.gamma.astype(jnp.float32) \
            + p.beta.astype(jnp.float32)
        new_mean = decay * p.running_mean + (1 - decay) * mean
        new_var = decay * p.running_var + (1 - decay) * var
        return out, (mean, var) if train else (p.running_mean,
                                               p.running_var), \
            (new_mean, new_var)

    ya = conv1x1(x.astype(jnp.float32), wa.astype(jnp.float32),
                 stride).astype(x.dtype)
    za, (mua, vara), ra = bn(ya, bn_a, train)
    za = jnp.maximum(za, 0.0)
    yb = conv3x3(za.astype(x.dtype).astype(jnp.float32),
                 wb.astype(jnp.float32)).astype(x.dtype)
    zb, (mub, varb), rb = bn(yb, bn_b, train)
    zb = jnp.maximum(zb, 0.0)
    yc = conv1x1(zb.astype(x.dtype).astype(jnp.float32),
                 wc.astype(jnp.float32)).astype(x.dtype)
    zc, (muc, varc), rc = bn(yc, bn_c, train)
    if w_skip is not None:
        ys = conv1x1(x.astype(jnp.float32), w_skip.astype(jnp.float32),
                     stride).astype(x.dtype)
        zs, _, rs = bn(ys, bn_skip, train)
        shortcut = zs
    else:
        shortcut = x.astype(jnp.float32)
    out = jnp.maximum(zc + shortcut, 0.0).astype(x.dtype)
    stats = (ra[0], ra[1], rb[0], rb[1], rc[0], rc[1])
    if w_skip is not None:
        stats = stats + (rs[0], rs[1])
    return out, stats
