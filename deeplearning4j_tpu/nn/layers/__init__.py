"""Functional layer math (forward only — backward comes from jax.grad).

TPU-native replacement for deeplearning4j-nn/.../nn/layers/* hand-written
forward/backward pairs and the deeplearning4j-cuda cuDNN helpers: each op here
is a pure function lowered by XLA onto the MXU/VPU; autodiff replaces every
`backpropGradient` in the reference.
"""
