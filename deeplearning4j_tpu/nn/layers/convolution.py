"""Convolution + pooling ops (NCHW, matching the reference's layout).

TPU-native equivalent of:
- CudnnConvolutionHelper (deeplearning4j-cuda/.../convolution/CudnnConvolutionHelper.java:54-480)
  and the im2col+gemm fallback (ConvolutionLayer.java:197-221)
  -> `jax.lax.conv_general_dilated`, which XLA tiles directly onto the MXU —
  no algo selection, workspace management, or im2col materialization needed.
- CudnnSubsamplingHelper (.../subsampling/CudnnSubsamplingHelper.java:49-280)
  -> `jax.lax.reduce_window`.

ConvolutionMode semantics (ref: nn/conf/ConvolutionMode.java + InputTypeUtil.java):
- "truncate": explicit padding, out = floor((in + 2p - k)/s) + 1
- "strict":   explicit padding, requires (in + 2p - k) % s == 0
- "same":     out = ceil(in/s), asymmetric padding computed by XLA ("SAME")
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

DIMSPEC_2D = ("NCHW", "OIHW", "NCHW")
DIMSPEC_1D = ("NCW", "OIW", "NCW")


def conv_out_size(in_size: int, k: int, s: int, p: int, d: int, mode: str) -> int:
    eff_k = k + (k - 1) * (d - 1)
    if mode == "same":
        return -(-in_size // s)  # ceil
    if mode == "strict":
        if (in_size + 2 * p - eff_k) % s != 0:
            raise ValueError(
                f"ConvolutionMode strict: (in={in_size} + 2*p={p} - k={eff_k}) "
                f"not divisible by stride {s}"
            )
        return (in_size + 2 * p - eff_k) // s + 1
    # truncate
    out = (in_size + 2 * p - eff_k) // s + 1
    if out < 1:
        raise ValueError(
            f"Conv/pool output size {out} < 1 (in={in_size}, kernel={eff_k}, "
            f"stride={s}, padding={p}) — input too small for this architecture"
        )
    return out


def _padding_arg(kernel, stride, padding, dilation, mode: str):
    if mode == "same":
        return "SAME"
    return [(int(p), int(p)) for p in padding]


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None,
    stride: Sequence[int],
    padding: Sequence[int],
    dilation: Sequence[int] = (1, 1),
    mode: str = "truncate",
) -> jax.Array:
    """2-D convolution, x:[N,C,H,W], w:[O,I,kH,kW] -> [N,O,H',W']."""
    y = lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(int(s) for s in stride),
        padding=_padding_arg(w.shape[2:], stride, padding, dilation, mode),
        rhs_dilation=tuple(int(d) for d in dilation),
        dimension_numbers=DIMSPEC_2D,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def deconv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None,
    stride: Sequence[int],
    padding: Sequence[int],
    mode: str = "truncate",
) -> jax.Array:
    """2-D transposed convolution ("deconvolution", ref Deconvolution2D layer)."""
    pad = "SAME" if mode == "same" else [(int(p), int(p)) for p in padding]
    y = lax.conv_transpose(
        x,
        w,
        strides=tuple(int(s) for s in stride),
        padding=pad,
        dimension_numbers=DIMSPEC_2D,
        transpose_kernel=True,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def conv1d(x, w, b, stride: int, padding: int, dilation: int = 1, mode: str = "truncate"):
    """1-D convolution over [N, C, W]."""
    pad = "SAME" if mode == "same" else [(int(padding), int(padding))]
    y = lax.conv_general_dilated(
        x,
        w,
        window_strides=(int(stride),),
        padding=pad,
        rhs_dilation=(int(dilation),),
        dimension_numbers=DIMSPEC_1D,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1)
    return y


def _pool_padding(mode: str, padding, nd: int):
    if mode == "same":
        return "SAME"
    return [(0, 0), (0, 0)] + [(int(p), int(p)) for p in padding]


def max_pool2d(x, kernel, stride, padding, mode="truncate"):
    dims = (1, 1) + tuple(int(k) for k in kernel)
    strides = (1, 1) + tuple(int(s) for s in stride)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, dims, strides, _pool_padding(mode, padding, 2)
    )


def avg_pool2d(x, kernel, stride, padding, mode="truncate", count_include_pad=True):
    dims = (1, 1) + tuple(int(k) for k in kernel)
    strides = (1, 1) + tuple(int(s) for s in stride)
    pad = _pool_padding(mode, padding, 2)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
    if count_include_pad and mode != "same":
        denom = float(kernel[0] * kernel[1])
        return summed / denom
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
    return summed / counts


def pnorm_pool2d(x, kernel, stride, padding, p: float, mode="truncate", eps=1e-8):
    """P-norm pooling (ref: SubsamplingLayer PoolingType.PNORM)."""
    dims = (1, 1) + tuple(int(k) for k in kernel)
    strides = (1, 1) + tuple(int(s) for s in stride)
    pad = _pool_padding(mode, padding, 2)
    powed = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
    return jnp.clip(powed, eps, None) ** (1.0 / p)


def upsample2d(x, size: Sequence[int]):
    """Nearest-neighbour upsampling (ref: Upsampling2D layer)."""
    sh, sw = int(size[0]), int(size[1])
    return jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)


def zero_pad2d(x, pad: Sequence[int]):
    """Zero padding [top, bottom, left, right] (ref: ZeroPaddingLayer)."""
    t, bm, l, r = (int(p) for p in pad)
    return jnp.pad(x, ((0, 0), (0, 0), (t, bm), (l, r)))


def space_to_depth(x, block: int):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // block, block, w // block, block)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * block * block, h // block, w // block)
