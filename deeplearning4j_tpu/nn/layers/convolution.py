"""Convolution + pooling ops (NCHW API, optional NHWC internal layout).

TPU-native equivalent of:
- CudnnConvolutionHelper (deeplearning4j-cuda/.../convolution/CudnnConvolutionHelper.java:54-480)
  and the im2col+gemm fallback (ConvolutionLayer.java:197-221)
  -> `jax.lax.conv_general_dilated`, which XLA tiles directly onto the MXU —
  no algo selection, workspace management, or im2col materialization needed.
- CudnnSubsamplingHelper (.../subsampling/CudnnSubsamplingHelper.java:49-280)
  -> `jax.lax.reduce_window`.

data_format: every op takes "NCHW" (DL4J parity layout, default) or "NHWC"
(channel-minor). On TPU the VPU lanes run along the minor dimension, so
NHWC keeps per-channel work (BatchNorm stats, bias adds) lane-aligned and
measured ~10% faster end-to-end on ResNet50; weights stay [O,I,kH,kW] in
the param pytree either way (serialization/import parity) — the OIHW->HWIO
transpose below is folded into XLA's one-time weight-prep copy.

ConvolutionMode semantics (ref: nn/conf/ConvolutionMode.java + InputTypeUtil.java):
- "truncate": explicit padding, out = floor((in + 2p - k)/s) + 1
- "strict":   explicit padding, requires (in + 2p - k) % s == 0
- "same":     out = ceil(in/s), asymmetric padding computed by XLA ("SAME")
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

DIMSPEC_1D = ("NCW", "OIW", "NCW")


def _dimspec_2d(data_format: str):
    if data_format == "NHWC":
        return ("NHWC", "HWIO", "NHWC")
    return ("NCHW", "OIHW", "NCHW")


def _to_hwio(w: jax.Array, data_format: str) -> jax.Array:
    """Params store conv kernels as [O,I,kH,kW] (DL4J layout) regardless of
    data_format; rearrange for the NHWC path."""
    return w.transpose(2, 3, 1, 0) if data_format == "NHWC" else w


def _bias_shape(ndim: int, data_format: str):
    shape = [1] * ndim
    shape[3 if data_format == "NHWC" else 1] = -1
    return shape


def conv_out_size(in_size: int, k: int, s: int, p: int, d: int, mode: str) -> int:
    eff_k = k + (k - 1) * (d - 1)
    if mode == "same":
        return -(-in_size // s)  # ceil
    if mode == "strict":
        if (in_size + 2 * p - eff_k) % s != 0:
            raise ValueError(
                f"ConvolutionMode strict: (in={in_size} + 2*p={p} - k={eff_k}) "
                f"not divisible by stride {s}"
            )
        return (in_size + 2 * p - eff_k) // s + 1
    # truncate
    out = (in_size + 2 * p - eff_k) // s + 1
    if out < 1:
        raise ValueError(
            f"Conv/pool output size {out} < 1 (in={in_size}, kernel={eff_k}, "
            f"stride={s}, padding={p}) — input too small for this architecture"
        )
    return out


def _padding_arg(kernel, stride, padding, dilation, mode: str):
    if mode == "same":
        return "SAME"
    return [(int(p), int(p)) for p in padding]


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None,
    stride: Sequence[int],
    padding: Sequence[int],
    dilation: Sequence[int] = (1, 1),
    mode: str = "truncate",
    data_format: str = "NCHW",
) -> jax.Array:
    """2-D convolution, x:[N,C,H,W] (or [N,H,W,C]), w:[O,I,kH,kW]."""
    y = lax.conv_general_dilated(
        x,
        _to_hwio(w, data_format),
        window_strides=tuple(int(s) for s in stride),
        padding=_padding_arg(w.shape[2:], stride, padding, dilation, mode),
        rhs_dilation=tuple(int(d) for d in dilation),
        dimension_numbers=_dimspec_2d(data_format),
    )
    if b is not None:
        y = y + b.reshape(_bias_shape(4, data_format))
    return y


def deconv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None,
    stride: Sequence[int],
    padding: Sequence[int],
    mode: str = "truncate",
    data_format: str = "NCHW",
) -> jax.Array:
    """2-D transposed convolution ("deconvolution", ref Deconvolution2D layer)."""
    pad = "SAME" if mode == "same" else [(int(p), int(p)) for p in padding]
    y = lax.conv_transpose(
        x,
        _to_hwio(w, data_format),
        strides=tuple(int(s) for s in stride),
        padding=pad,
        dimension_numbers=_dimspec_2d(data_format),
        transpose_kernel=True,
    )
    if b is not None:
        y = y + b.reshape(_bias_shape(4, data_format))
    return y


def conv1d(x, w, b, stride: int, padding: int, dilation: int = 1, mode: str = "truncate"):
    """1-D convolution over [N, C, W]."""
    pad = "SAME" if mode == "same" else [(int(padding), int(padding))]
    y = lax.conv_general_dilated(
        x,
        w,
        window_strides=(int(stride),),
        padding=pad,
        rhs_dilation=(int(dilation),),
        dimension_numbers=DIMSPEC_1D,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1)
    return y


def _window(kernel, data_format: str):
    k = tuple(int(v) for v in kernel)
    return (1, 1) + k if data_format == "NCHW" else (1,) + k + (1,)


def _pool_padding(mode: str, padding, data_format: str):
    if mode == "same":
        return "SAME"
    pads = [(int(p), int(p)) for p in padding]
    if data_format == "NCHW":
        return [(0, 0), (0, 0)] + pads
    return [(0, 0)] + pads + [(0, 0)]


def max_pool2d(x, kernel, stride, padding, mode="truncate", data_format="NCHW"):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, _window(kernel, data_format),
        _window(stride, data_format), _pool_padding(mode, padding, data_format)
    )


def avg_pool2d(x, kernel, stride, padding, mode="truncate",
               count_include_pad=True, data_format="NCHW"):
    dims = _window(kernel, data_format)
    strides = _window(stride, data_format)
    pad = _pool_padding(mode, padding, data_format)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
    if count_include_pad and mode != "same":
        denom = float(kernel[0] * kernel[1])
        return summed / denom
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
    return summed / counts


def pnorm_pool2d(x, kernel, stride, padding, p: float, mode="truncate",
                 eps=1e-8, data_format="NCHW"):
    """P-norm pooling (ref: SubsamplingLayer PoolingType.PNORM)."""
    powed = lax.reduce_window(
        jnp.abs(x) ** p, 0.0, lax.add, _window(kernel, data_format),
        _window(stride, data_format), _pool_padding(mode, padding, data_format))
    return jnp.clip(powed, eps, None) ** (1.0 / p)


def upsample2d(x, size: Sequence[int], data_format="NCHW"):
    """Nearest-neighbour upsampling (ref: Upsampling2D layer)."""
    sh, sw = int(size[0]), int(size[1])
    h_ax, w_ax = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.repeat(jnp.repeat(x, sh, axis=h_ax), sw, axis=w_ax)


def zero_pad2d(x, pad: Sequence[int], data_format="NCHW"):
    """Zero padding [top, bottom, left, right] (ref: ZeroPaddingLayer)."""
    t, bm, l, r = (int(p) for p in pad)
    if data_format == "NHWC":
        return jnp.pad(x, ((0, 0), (t, bm), (l, r), (0, 0)))
    return jnp.pad(x, ((0, 0), (0, 0), (t, bm), (l, r)))


def space_to_depth(x, block: int, data_format="NCHW"):
    if data_format == "NHWC":
        n, h, w, c = x.shape
        x = x.reshape(n, h // block, block, w // block, block, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(n, h // block, w // block, c * block * block)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // block, block, w // block, block)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * block * block, h // block, w // block)
